"""Mixture-of-experts MLP (mixtral family) — dense-mixture, TPU-first.

The reference has no model code at all (SURVEY §0); MoE enters through the
framework's model-family coverage (mixtral-8x7b preset, llama.py) and the
`expert` mesh axis (SURVEY §2.3: expert parallelism "only if MoE models
are added" — they are).

Design: DENSE mixture. Every expert processes every token; the top-k
router gates (zeros outside the selected experts) weight the combine. Why
this is the TPU-right shape for serving:

  - A serving batch of B slots × top-2 routing touches essentially every
    expert every step, so all expert weights stream from HBM regardless —
    the decode step stays bandwidth-bound and skipping compute for
    unselected (token, expert) pairs saves no HBM traffic.
  - The expert dim becomes a leading batch dim of ONE big dot_general per
    projection — the MXU sees [experts] × [tokens, embed] @ [embed, ffn]
    batched matmuls, no gathers, no ragged dispatch, no recompiles.
  - Sharding: experts map to the `expert` mesh axis and each expert's ffn
    dim to `model` (parallel/sharding.py rules); XLA derives the combine
    all-reduce from the shardings, exactly like the dense-MLP TP path.

PREFILL is the exception: it is compute-bound (S large), and the dense
mixture pays num_experts/top_k extra FLOPs (4x for mixtral-8x7b). There
moe_mlp routes through capacity-factor token DISPATCH (moe_mlp_dispatch):
tokens are gathered into a static [experts, capacity, embed] buffer (rank
computed with a one-hot cumsum — no ragged shapes, no recompiles), each
expert runs one batched matmul over just its tokens, and a scatter-add
combines the gated results. Under an `expert` mesh axis the gather/
scatter become XLA-inserted all-to-alls along it, exactly the GShard/
Switch dispatch pattern. Tokens past an expert's capacity are dropped
(standard switch semantics); capacity_factor trades that tail loss
against the FLOP saving.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.quant import QuantizedTensor

# Per-expert buffer = ceil(T * top_k / X * CAPACITY_FACTOR) tokens.
# Capacity-factor dispatch is LOSSY under routing imbalance: (token,
# expert) pairs past an expert's capacity contribute nothing (standard
# switch semantics, no renormalization). The default of 2.0 keeps the
# drop tail negligible for mixtral-like routing while still saving
# X / (k * cf) = 2x prefill FLOPs; set `moe_capacity_factor` to
# num_experts / num_experts_per_tok for guaranteed-lossless dispatch
# (which also forfeits the FLOP saving — capacity then covers the
# worst case), or lower for more speed at more drop risk.
CAPACITY_FACTOR = 2.0
# Below this many tokens the dense mixture is used even at S > 1: the
# dispatch bookkeeping outweighs the matmul saving for tiny prefills.
MIN_DISPATCH_TOKENS = 64


def qmatmul_experts(x: jnp.ndarray, w) -> jnp.ndarray:
    """[B, S, D] @ per-expert [X, D, F] -> [B, S, X, F].

    QuantizedTensor experts keep the int8 payload as the dot operand (no
    bf16 materialization — same rule as ops/quant.py qmatmul); per-column
    scales [X, F] apply to the f32 accumulator."""
    if isinstance(w, QuantizedTensor):
        y = jax.lax.dot_general(
            x, w.q,
            dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [B, S, X, F]
        return (y * w.scale).astype(x.dtype)
    return jnp.einsum("bsd,xdf->bsxf", x, w)


def route_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Router logits [B, S, X] -> dense gates [B, S, X]: softmax over the
    top-k logits (mixtral semantics: normalize AFTER selection), zeros
    elsewhere. Static-shape: one_hot scatter, no gathers."""
    top_vals, top_idx = jax.lax.top_k(logits, k)          # [B, S, k]
    probs = jax.nn.softmax(top_vals, axis=-1)
    onehot = jax.nn.one_hot(top_idx, logits.shape[-1],
                            dtype=probs.dtype)            # [B, S, k, X]
    return jnp.einsum("bsk,bskx->bsx", probs, onehot)


def moe_mlp(x: jnp.ndarray, lp: dict, config) -> jnp.ndarray:
    """MoE FFN: [B, S, E] -> [B, S, E]. Dense mixture at decode
    (bandwidth-bound), capacity-factor dispatch at prefill
    (compute-bound) — see module docstring."""
    B, S, _ = x.shape
    if S > 1 and B * S >= MIN_DISPATCH_TOKENS:
        return moe_mlp_dispatch(x, lp, config)
    gates = route_top_k(
        jnp.asarray(x @ lp["router"], jnp.float32),
        config.num_experts_per_tok).astype(x.dtype)       # [B, S, X]
    h = jax.nn.silu(qmatmul_experts(x, lp["wg"])) * qmatmul_experts(
        x, lp["wu"])                                      # [B, S, X, F]
    # Per-expert down-projection then gated combine over experts.
    wd = lp["wd"]
    if isinstance(wd, QuantizedTensor):
        y = jax.lax.dot_general(
            h, wd.q,
            dimension_numbers=(((3,), (1,)), ((2,), (0,))),
            preferred_element_type=jnp.float32,
        )  # batch over experts: [X, B, S, E]
        y = (y * wd.scale[:, None, None, :]).astype(x.dtype)
        y = jnp.moveaxis(y, 0, 2)                         # [B, S, X, E]
    else:
        y = jnp.einsum("bsxf,xfe->bsxe", h, wd)
    return jnp.einsum("bsxe,bsx->bse", y, gates)


def _expert_matmul(xg: jnp.ndarray, w) -> jnp.ndarray:
    """Per-expert batched matmul: [X, C, A] @ [X, A, F] -> [X, C, F]."""
    if isinstance(w, QuantizedTensor):
        y = jax.lax.dot_general(
            xg, w.q,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return (y * w.scale[:, None, :]).astype(xg.dtype)
    return jnp.einsum("xca,xaf->xcf", xg, w)


def moe_mlp_dispatch(x: jnp.ndarray, lp: dict, config) -> jnp.ndarray:
    """Capacity-factor token dispatch (GShard/Switch shape, static sizes).

    Each (token, choice) pair is ranked within its expert by a one-hot
    cumsum; pairs past the expert's capacity are dropped. Experts compute
    ONE batched matmul over their gathered tokens — FLOPs scale with
    top_k * capacity_factor instead of num_experts — and a scatter-add
    puts the gated outputs back in token order.
    """
    B, S, E = x.shape
    X = config.num_experts
    k = config.num_experts_per_tok
    cf = getattr(config, "moe_capacity_factor", None) or CAPACITY_FACTOR
    T = B * S
    C = min(T, math.ceil(T * k / X * cf))

    xf = x.reshape(T, E)
    logits = jnp.asarray(xf @ lp["router"], jnp.float32)      # [T, X]
    top_vals, top_idx = jax.lax.top_k(logits, k)              # [T, k]
    probs = jax.nn.softmax(top_vals, axis=-1)                 # mixtral renorm

    flat_expert = top_idx.reshape(-1)                         # [T*k]
    flat_token = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    flat_gate = probs.reshape(-1).astype(x.dtype)

    # Rank of each pair within its expert = how many earlier pairs chose
    # the same expert (one-hot cumsum: static shapes, no sort).
    onehot = jax.nn.one_hot(flat_expert, X, dtype=jnp.int32)  # [T*k, X]
    before = jnp.cumsum(onehot, axis=0) - onehot
    rank = jnp.take_along_axis(before, flat_expert[:, None], 1)[:, 0]
    keep = rank < C
    # Slot in the [X * C] dispatch buffer; dropped pairs target a trash
    # slot (index X*C) so every scatter stays in bounds and static.
    slot = jnp.where(keep, flat_expert * C + rank, X * C)

    token_for_slot = jnp.zeros((X * C + 1,), jnp.int32).at[slot].set(
        flat_token)
    gate_for_slot = jnp.zeros((X * C + 1,), x.dtype).at[slot].set(
        jnp.where(keep, flat_gate, 0).astype(x.dtype))

    xg = jnp.take(xf, token_for_slot[:X * C], axis=0).reshape(X, C, E)
    h = jax.nn.silu(_expert_matmul(xg, lp["wg"])) * _expert_matmul(
        xg, lp["wu"])                                         # [X, C, F]
    y = _expert_matmul(h, lp["wd"])                           # [X, C, E]

    weighted = y.reshape(X * C, E) * gate_for_slot[:X * C, None]
    out = jnp.zeros((T, E), x.dtype).at[token_for_slot[:X * C]].add(weighted)
    return out.reshape(B, S, E)
