"""Model zoo: TPU-native decoder-only LMs the engine can serve.

The reference contains no model code at all — it proxies every request to an
external OpenAI-compatible server (reference: src/provider.ts:210-214,
src/constants.ts:22-29). These models are the in-process replacement: pure
functional JAX (params are pytrees, forward is a jittable function), layers
stacked and scanned for O(1) compile cost in depth, every parameter tagged
with logical sharding axes (parallel/sharding.py).
"""

from symmetry_tpu.models.llama import (
    KVCache,
    ModelConfig,
    PRESETS,
    forward,
    init_cache,
    init_params,
    param_logical_axes,
    preset,
)

__all__ = [
    "KVCache",
    "ModelConfig",
    "PRESETS",
    "forward",
    "init_cache",
    "init_params",
    "param_logical_axes",
    "preset",
]
