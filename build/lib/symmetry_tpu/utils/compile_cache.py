"""Persistent XLA compilation cache for production engine processes.

The serving warmup compiles the full (prefill-batch × bucket) grid plus
the decode program — ~90 s of a measured ~94 s provider startup on a real
chip (round-3 verdict #4). JAX's persistent compilation cache keys entries
by HLO + compile options + backend, so a shared directory is safe across
configs: a different mesh/dtype/bucket grid simply misses and fills its
own entries. tests/conftest.py wires the same cache for the test suite;
this module is the production-path equivalent (engine host, in-process
backend, bench).

The cache is advisory: a backend whose executables can't be serialized
(or an unwritable directory) degrades to cold compiles with a warning,
never a failure.
"""

from __future__ import annotations

import os
from typing import Any

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "symmetry_tpu", "xla")


def enable_compile_cache(tpu_cfg: Any = None) -> str | None:
    """Point JAX's persistent compilation cache at a stable directory.

    `tpu_cfg.compile_cache` (provider.yaml `tpu:` section): True → the
    default directory, a string → that directory, False → disabled.
    Returns the directory in use, or None when disabled/unavailable.
    Call before the first jit compile (startup) for full effect.
    """
    setting = True if tpu_cfg is None else getattr(tpu_cfg, "compile_cache",
                                                   True)
    if setting is False:
        return None
    # An environment-provided cache wins (tests propagate theirs to engine
    # subprocesses through JAX_COMPILATION_CACHE_DIR; jax reads it at
    # import, so it is already in effect — don't repoint it).
    env_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env_dir:
        return env_dir
    cache_dir = setting if isinstance(setting, str) else DEFAULT_CACHE_DIR
    cache_dir = os.path.expanduser(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Serving compiles are worth persisting even when fast: the grid
        # is wide, and the default 1 s floor would skip the small-bucket
        # insert programs that still add up across a restart.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return cache_dir
    except Exception as exc:  # noqa: BLE001 — cache is advisory
        from symmetry_tpu.utils.logging import logger

        logger.warning(f"persistent compile cache unavailable: {exc}")
        return None
