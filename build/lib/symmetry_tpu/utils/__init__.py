from symmetry_tpu.utils.logging import Logger, LogLevel, logger
from symmetry_tpu.utils.json import safe_parse_json, dumps

__all__ = ["Logger", "LogLevel", "logger", "safe_parse_json", "dumps"]
