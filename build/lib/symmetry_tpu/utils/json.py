"""JSON helpers (capability parity with reference src/utils.ts:4-14)."""

from __future__ import annotations

import json
from typing import Any


def safe_parse_json(data: str | bytes | None) -> Any | None:
    """Parse JSON, returning None on any failure (reference: src/utils.ts:4-10)."""
    if data is None:
        return None
    try:
        return json.loads(data)
    except (json.JSONDecodeError, TypeError, UnicodeDecodeError, ValueError):
        return None


def dumps(obj: Any) -> bytes:
    """Compact UTF-8 JSON encoding for the wire."""
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False).encode("utf-8")
