"""The TPU inference engine — the half of the system the reference never had.

The reference delegated all compute to an external OpenAI-compatible HTTP
server (reference: src/provider.ts:210-214). This package replaces that leg
with an in-process JAX/XLA engine: HF safetensors stream straight onto a
pjit-sharded mesh (weights.py), prefill/decode run as jitted pure functions
over a slot-based KV cache, and a continuous-batching scheduler turns slots
into per-request token streams (SURVEY §7 stages 4-5).
"""
