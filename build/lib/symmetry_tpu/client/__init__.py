from symmetry_tpu.client.client import SymmetryClient

__all__ = ["SymmetryClient"]
