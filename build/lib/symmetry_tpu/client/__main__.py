"""Client CLI: one-shot or interactive chat through the Symmetry network.

    python -m symmetry_tpu.client --server tcp://host:4848 --server-key HEX \
        --model llama3:8b "why is the sky blue?"
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from symmetry_tpu.client.client import SymmetryClient


async def run(args: argparse.Namespace) -> None:
    from symmetry_tpu.transport import transport_for

    client = SymmetryClient(transport=transport_for(args.server))
    server_key = bytes.fromhex(args.server_key)
    if args.list_models:
        for row in await client.list_models(args.server, server_key):
            print(row)
        return
    details = await client.request_provider(args.server, server_key, args.model)
    print(f"[assigned provider {details.peer_key[:12]}… at {details.address}]",
          file=sys.stderr)
    session = await client.connect(details)
    async with session:
        if args.prompt:
            async for delta in session.chat([{"role": "user", "content": args.prompt}]):
                print(delta, end="", flush=True)
            print()
            return
        history: list[dict[str, str]] = []
        while True:
            try:
                user = input("you> ")
            except (EOFError, KeyboardInterrupt):
                return
            if not user.strip():
                continue
            await session.new_conversation()
            history.append({"role": "user", "content": user})
            out = []
            async for delta in session.chat(history):
                out.append(delta)
                print(delta, end="", flush=True)
            print()
            history.append({"role": "assistant", "content": "".join(out)})


def main() -> None:
    parser = argparse.ArgumentParser(prog="symmetry-client")
    parser.add_argument("--server", required=True, help="tcp://host:port")
    parser.add_argument("--server-key", required=True, help="server public key (hex)")
    parser.add_argument("--model", default=None)
    parser.add_argument("--list-models", action="store_true")
    parser.add_argument("prompt", nargs="?", default=None)
    args = parser.parse_args()
    asyncio.run(run(args))


if __name__ == "__main__":
    main()
