"""Transport abstractions.

The reference's transport is the hyperswarm native stack (UDX reliable-UDP →
Noise secret-stream → DHT; SURVEY §1 layers A–C), reached only through
`swarm.join` + connection events. We make the transport an explicit, injectable
seam — the one good idea in the reference's test (it mocks hyperswarm whole,
__test__/cli.test.ts:4-13), generalized: protocol and node logic run unchanged
over in-memory pipes (tests), TCP (production), or a future C++/UDP transport.

A Connection carries opaque *frames* (bytes in, bytes out, boundaries
preserved); encryption layers above it (see symmetry_tpu.network.peer).
"""

from __future__ import annotations

import abc
from typing import AsyncIterator, Awaitable, Callable


class Connection(abc.ABC):
    """A reliable, ordered, frame-boundary-preserving duplex channel."""

    @abc.abstractmethod
    async def send(self, frame: bytes) -> None:
        """Send one frame. Applies backpressure (awaits drain) when buffers fill."""

    @abc.abstractmethod
    async def recv(self) -> bytes | None:
        """Receive one frame, or None on clean EOF."""

    @abc.abstractmethod
    async def close(self) -> None: ...

    @property
    @abc.abstractmethod
    def closed(self) -> bool: ...

    @property
    def remote_address(self) -> str:
        return "?"

    async def __aiter__(self) -> AsyncIterator[bytes]:
        while True:
            frame = await self.recv()
            if frame is None:
                return
            yield frame


ConnectionHandler = Callable[[Connection], Awaitable[None]]


class Listener(abc.ABC):
    """An accepting endpoint bound to an address."""

    @property
    @abc.abstractmethod
    def address(self) -> str:
        """Dialable address string, e.g. 'tcp://10.0.0.2:31337' or 'mem://a'."""

    @abc.abstractmethod
    async def close(self) -> None: ...


class Transport(abc.ABC):
    """Factory for listeners and outbound connections."""

    scheme: str = "?"

    @abc.abstractmethod
    async def listen(self, address: str, handler: ConnectionHandler) -> Listener: ...

    @abc.abstractmethod
    async def dial(self, address: str) -> Connection: ...
