from symmetry_tpu.transport.base import Connection, Listener, Transport
from symmetry_tpu.transport.memory import MemoryTransport, memory_pair
from symmetry_tpu.transport.tcp import TcpTransport


def transport_for(address: str) -> Transport:
    """Pick a transport by address scheme: tcp:// (default) or udp:// (native
    C++ udpstream, transport/udp.py). mem:// is rejected: MemoryTransport
    registries are instance-local, so a fresh instance could never reach an
    existing listener — tests must inject their hub explicitly."""
    if address.startswith("udp://"):
        from symmetry_tpu.transport.udp import UdpTransport

        return UdpTransport()
    if address.startswith("mem://"):
        raise ValueError(
            "mem:// requires passing the shared MemoryTransport instance")
    return TcpTransport()


__all__ = [
    "Connection",
    "Listener",
    "Transport",
    "MemoryTransport",
    "memory_pair",
    "TcpTransport",
    "transport_for",
]
