from symmetry_tpu.identity.identity import Identity, discovery_key
from symmetry_tpu.identity.noise import (
    HandshakeError,
    SecureSession,
    client_handshake,
    server_handshake,
)

__all__ = [
    "Identity",
    "discovery_key",
    "HandshakeError",
    "SecureSession",
    "client_handshake",
    "server_handshake",
]
