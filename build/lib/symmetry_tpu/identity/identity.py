"""Node identity: seeded Ed25519 keypairs + discovery keys.

Capability parity with the reference's use of hypercore-crypto
(reference: src/provider.ts:41-44, global.d.ts:37-50):

  - `crypto.keyPair(seed)`      → `Identity.from_seed(seed)` (deterministic)
  - `crypto.discoveryKey(pub)`  → `discovery_key(pub)` = BLAKE2b-32 of the
                                   public key under a fixed personalization
  - `crypto.verify(msg,sig,pk)` → `Identity.verify(...)`

The reference seeds the keypair from the provider *name* padded to 32 bytes
(src/provider.ts:41-43) — deterministic but collision-prone and guessable.
We keep seeded determinism as a capability (stable identity across restarts)
but derive the seed from a name + a locally persisted random secret, or accept
an explicit 32-byte seed.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)

_DISCOVERY_PERSON = b"symmetry-tpu"  # blake2b personalization (≤16 bytes)


def discovery_key(public_key: bytes) -> bytes:
    """32-byte topic derived from a public key.

    Same shape as hypercore-crypto's discoveryKey (BLAKE2b(pub) under a fixed
    personalization): peers can rendezvous on the hash of a key without
    revealing the key to the DHT.
    """
    return hashlib.blake2b(public_key, digest_size=32, person=_DISCOVERY_PERSON).digest()


def derive_seed(name: str, secret: bytes = b"") -> bytes:
    """Deterministic 32-byte seed from a human name (+ optional local secret).

    The secret enters as the blake2b MAC key, not by concatenation, so
    ('ab', b'c') and ('a', b'bc') cannot collide.
    """
    return hashlib.blake2b(
        name.encode("utf-8"), digest_size=32, key=secret[:64],
        person=b"symmetry-seed",
    ).digest()


@dataclass(frozen=True)
class Identity:
    """An Ed25519 signing identity. Equality/hash are by public key."""

    _private: Ed25519PrivateKey = field(compare=False)
    public_key: bytes = b""  # 32 raw bytes

    @classmethod
    def from_seed(cls, seed: bytes) -> "Identity":
        if len(seed) != 32:
            raise ValueError("seed must be exactly 32 bytes")
        priv = Ed25519PrivateKey.from_private_bytes(seed)
        pub = priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        return cls(priv, pub)

    @classmethod
    def from_name(cls, name: str, secret: bytes = b"") -> "Identity":
        return cls.from_seed(derive_seed(name, secret))

    @classmethod
    def generate(cls) -> "Identity":
        return cls.from_seed(os.urandom(32))

    def sign(self, message: bytes) -> bytes:
        return self._private.sign(message)

    @staticmethod
    def verify(message: bytes, signature: bytes, public_key: bytes) -> bool:
        """Verify a detached signature; False instead of raising on bad input."""
        try:
            Ed25519PublicKey.from_public_bytes(public_key).verify(signature, message)
            return True
        except (InvalidSignature, ValueError):
            return False

    @property
    def discovery_key(self) -> bytes:
        return discovery_key(self.public_key)

    @property
    def public_hex(self) -> str:
        return self.public_key.hex()

    def __repr__(self) -> str:  # never leak private material
        return f"Identity(pub={self.public_hex[:16]}…)"
