from symmetry_tpu.protocol.keys import MessageKey, SERVER_MESSAGE_KEYS
from symmetry_tpu.protocol.messages import Message, create_message, parse_message
from symmetry_tpu.protocol.framing import FrameReader, encode_frame, MAX_FRAME_SIZE

__all__ = [
    "MessageKey",
    "SERVER_MESSAGE_KEYS",
    "Message",
    "create_message",
    "parse_message",
    "FrameReader",
    "encode_frame",
    "MAX_FRAME_SIZE",
]
