"""Length-prefixed frame codec.

The reference writes raw JSON strings to the peer stream and hopes message
boundaries survive (src/provider.ts:97-108 writes, 110-115 parse of whole
`data` events). Here every payload travels as a frame:

    [4-byte big-endian length N][N bytes payload]

A frame payload is either plaintext JSON (pre-handshake) or ciphertext
(post-handshake, see symmetry_tpu.identity.noise). The codec is sans-IO:
`FrameReader.feed()` accepts arbitrary byte chunks and yields complete frames,
so it works over asyncio, tests, or a C++ transport equally.

A native C++ implementation of the same codec lives in native/; this module is
the always-available pure-Python path.
"""

from __future__ import annotations

import struct
from typing import Iterator

MAX_FRAME_SIZE = 32 * 1024 * 1024  # 32 MiB — bounds memory per peer
_HEADER = struct.Struct(">I")


class FrameError(ValueError):
    """Raised when a peer sends a malformed or oversized frame."""


def encode_frame(payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME_SIZE:
        raise FrameError(f"frame too large: {len(payload)}")
    return _HEADER.pack(len(payload)) + payload


class FrameReader:
    """Incremental frame parser. Feed bytes, iterate complete frames."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._need: int | None = None  # payload length of the frame in progress

    def feed(self, chunk: bytes) -> Iterator[bytes]:
        self._buf.extend(chunk)
        while True:
            if self._need is None:
                if len(self._buf) < _HEADER.size:
                    return
                (need,) = _HEADER.unpack_from(self._buf)
                if need > MAX_FRAME_SIZE:
                    # Don't poison state: a caller that keeps feeding after the
                    # error must not start buffering toward the bogus length.
                    raise FrameError(f"frame too large: {need}")
                self._need = need
                del self._buf[: _HEADER.size]
            if len(self._buf) < self._need:
                return
            payload = bytes(self._buf[: self._need])
            del self._buf[: self._need]
            self._need = None
            yield payload


