"""Multi-host serving: one logical provider backed by N JAX processes.

The reference's only multi-node story was many independent single-node
providers behind server routing (SURVEY §2.3). A multi-host TPU pod is
different: N host processes each own a slice of the devices, every jitted
computation must be entered by ALL processes in the same order, and only
rank 0 fronts the P2P network. Three pieces (SURVEY §7 stage 6 +
hard-part 2):

  1. `init_distributed` — jax.distributed bring-up (coordinator address,
     process count, rank), after which jax.devices() is the GLOBAL device
     set and arrays can span hosts.
  2. `build_multihost_mesh` — a hybrid mesh whose `data` axis spans hosts
     over DCN (no per-layer collectives cross hosts) while `context`/
     `model` stay inside each host's ICI domain (mesh_utils topology-aware
     ordering).
  3. `CommandLoop` — the rank-0 control plane: rank 0 decides engine calls
     (prefill/insert/decode/stop) from its scheduler; every process —
     including rank 0 — receives each command via a device-fabric broadcast
     and enters the identical jitted call. Workers never see the network.

Commands ride `multihost_utils.broadcast_one_to_all` as one fixed-shape
int32 vector (jit-friendly: same shape every step, no pickled metadata on
the hot path).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

from symmetry_tpu.parallel.mesh import AXIS_ORDER, MeshSpec
from symmetry_tpu.utils.logging import logger as log

# Command kinds (slot 0 of the broadcast vector).
CMD_IDLE = 0      # no-op heartbeat (keeps workers in lockstep while empty)
CMD_PREFILL = 1   # prefill + insert one request
CMD_DECODE = 2    # advance all slots one decode block
CMD_STOP = 3      # shut down the loop
CMD_WARMUP = 4    # precompile the decode program (pre-traffic)

# Vector layout: [kind, slot, true_len, bucket, temp_milli, top_p_milli,
#                 top_k, seed_or_-1, tokens...(max_bucket)]
_HEADER = 8


_distributed_up = False


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int, **kwargs: Any) -> None:
    """Bring up jax.distributed (idempotent per process — a provider
    restart re-enters this; jax raises on a second initialize)."""
    global _distributed_up
    if _distributed_up:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _distributed_up = True
    log.info(
        f"jax.distributed up: rank {process_id}/{num_processes}, "
        f"{jax.local_device_count()} local / {jax.device_count()} global devices")


def build_multihost_mesh(ici: MeshSpec | dict, dcn_data: int = 1):
    """Mesh whose `data` axis spans hosts (DCN) and the rest ICI.

    In a multi-process job the mesh MUST cover every global device — a mesh
    that misses a process leaves that rank with no addressable shard of any
    engine array, which fails at the first host read. `ici` describes ONE
    host's slice; dcn_data is the number of hosts on the data axis.
    """
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if isinstance(ici, dict):
        ici = MeshSpec.from_dict(ici)
    total = dcn_data * ici.size
    if jax.process_count() > 1 and total != jax.device_count():
        raise ValueError(
            f"multihost mesh ({dcn_data} hosts × ici {ici.shape()}) covers "
            f"{total} devices but the job has {jax.device_count()} — every "
            f"global device must be in the mesh")
    ici_shape = tuple(getattr(ici, a) for a in AXIS_ORDER)
    # data is the DCN-crossing axis (stage PP over DCN would be the other
    # legal choice; this helper builds data-over-DCN meshes)
    dcn_shape = tuple(dcn_data if a == "data" else 1 for a in AXIS_ORDER)
    if dcn_data > 1:
        try:
            # TPU pods: DCN granule = slice (device.slice_index).
            devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=jax.devices())
        except ValueError:
            # Backends without slice indices (CPU tests, single-slice jobs
            # spanning hosts): granule = process.
            devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=jax.devices(),
                process_is_granule=True)
    else:
        devices = mesh_utils.create_device_mesh(ici_shape,
                                                devices=jax.devices()[:ici.size])
    return Mesh(devices, AXIS_ORDER)


@dataclass
class Command:
    kind: int
    slot: int = 0
    true_len: int = 0
    bucket: int = 0
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int | None = None
    tokens: np.ndarray | None = None  # [true_len] int32

    def encode(self, max_bucket: int) -> np.ndarray:
        vec = np.zeros((_HEADER + max_bucket,), np.int32)
        vec[0] = self.kind
        vec[1] = self.slot
        vec[2] = self.true_len
        vec[3] = self.bucket
        vec[4] = int(self.temperature * 1000)
        vec[5] = int(self.top_p * 1000)
        vec[6] = self.top_k
        vec[7] = -1 if self.seed is None else self.seed
        if self.tokens is not None:
            vec[_HEADER:_HEADER + len(self.tokens)] = self.tokens
        return vec

    @classmethod
    def decode(cls, vec: np.ndarray) -> "Command":
        kind, slot, true_len, bucket = (int(vec[0]), int(vec[1]),
                                        int(vec[2]), int(vec[3]))
        seed = int(vec[7])
        return cls(
            kind=kind, slot=slot, true_len=true_len, bucket=bucket,
            temperature=vec[4] / 1000.0, top_p=vec[5] / 1000.0,
            top_k=int(vec[6]), seed=None if seed < 0 else seed,
            tokens=np.asarray(vec[_HEADER:_HEADER + true_len], np.int32),
        )


class CommandLoop:
    """Lockstep engine driver: rank 0 leads, all ranks follow.

    Rank 0 calls `lead(cmd)`; workers run `follow_forever()`. Both paths
    end in identical `InferenceEngine` method calls, which is what keeps
    every process entering the same jitted computations in the same order
    (the SPMD contract of multi-host JAX).
    """

    def __init__(self, engine, *, is_coordinator: bool) -> None:
        self.engine = engine
        self.is_coordinator = is_coordinator
        self.max_bucket = max(engine.prefill_buckets)

    # -------------------------------------------------------------- shared

    def _execute(self, cmd: Command):
        from symmetry_tpu.engine.engine import SamplingParams

        if cmd.kind == CMD_PREFILL:
            sampling = SamplingParams(
                temperature=cmd.temperature, top_p=cmd.top_p,
                top_k=cmd.top_k, seed=cmd.seed)
            return self.engine.prefill_and_insert(
                cmd.slot, list(map(int, cmd.tokens)), sampling)
        if cmd.kind == CMD_DECODE:
            return self.engine.decode_steps()
        if cmd.kind == CMD_WARMUP:
            return self.engine.warmup()
        return None

    def _broadcast(self, vec: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.broadcast_one_to_all(vec,
                                                 is_source=self.is_coordinator))

    # -------------------------------------------------------------- rank 0

    def lead(self, cmd: Command):
        """Broadcast a command and execute it locally (rank 0 only).

        Executes the DECODED round-trip of the wire bytes, not the original
        command — the milli-unit quantization of temperature/top_p must be
        identical on every rank or the replicated state diverges.
        """
        assert self.is_coordinator
        vec = cmd.encode(self.max_bucket)
        self._broadcast(vec)
        return self._execute(Command.decode(vec))

    def idle_tick(self) -> None:
        """Heartbeat while no requests are active: workers sit inside the
        broadcast collective, and distributed runtimes time out a collective
        that rank 0 never enters — tick it periodically."""
        assert self.is_coordinator
        self._broadcast(Command(kind=CMD_IDLE).encode(self.max_bucket))

    def stop(self) -> None:
        if self.is_coordinator:
            self._broadcast(Command(kind=CMD_STOP).encode(self.max_bucket))

    # -------------------------------------------------------------- workers

    def follow_forever(self) -> None:
        """Worker loop: receive and mirror rank 0's engine calls."""
        assert not self.is_coordinator
        zero = np.zeros((_HEADER + self.max_bucket,), np.int32)
        while True:
            cmd = Command.decode(self._broadcast(zero))
            if cmd.kind == CMD_STOP:
                return
            self._execute(cmd)


class MultihostEngine:
    """Engine facade for the scheduler on rank 0: every call is led through
    the CommandLoop so worker processes stay in lockstep. Exposes the same
    surface Scheduler uses (prefill_and_insert / decode_steps / metadata).
    """

    def __init__(self, loop: CommandLoop) -> None:
        self._loop = loop
        eng = loop.engine
        self.tokenizer = eng.tokenizer
        self.max_slots = eng.max_slots
        self.max_seq_len = eng.max_seq_len
        self.decode_block = eng.decode_block
        self.slot_capacity = eng.slot_capacity
        self.prefill_buckets = eng.prefill_buckets

    def prefill_and_insert(self, slot: int, prompt_ids, sampling) -> int:
        n = len(prompt_ids)
        bucket = self._loop.engine.bucket_for(n)
        seed = sampling.seed
        if seed is None:
            # Pin per-request entropy HERE: each process has different local
            # entropy, and an unseeded prefill executed per-process would
            # diverge the replicated state. Rank 0 chooses, all follow.
            seed = int.from_bytes(os.urandom(3), "little")
        # Client-controlled: fold into the non-negative int32 range the wire
        # slot carries (negative would decode as None → per-rank entropy;
        # >= 2^31 would overflow before the broadcast).
        seed = seed % (2**31)
        cmd = Command(
            kind=CMD_PREFILL, slot=slot, true_len=n, bucket=bucket,
            temperature=sampling.temperature, top_p=sampling.top_p,
            top_k=sampling.top_k, seed=seed,
            tokens=np.asarray(prompt_ids, np.int32))
        return self._loop.lead(cmd)

    def decode_steps(self) -> np.ndarray:
        return self._loop.lead(Command(kind=CMD_DECODE))

    def decode_steps_dispatch(self) -> np.ndarray:
        """Scheduler's double-buffer hook. Multihost decode must complete
        the cross-process command round before returning, so there is no
        async lookahead here — the already-materialized token block is
        returned and the scheduler's np.asarray on it is a no-op."""
        return self.decode_steps()

    def release_slot(self, slot: int) -> None:
        """Host-side no-op (engine.release_slot); nothing to broadcast."""
        self._loop.engine.release_slot(slot)

    def warmup(self) -> None:
        self._loop.lead(Command(kind=CMD_WARMUP))

    def idle_tick(self) -> None:
        self._loop.idle_tick()

    def slot_length(self, slot: int) -> int:
        return self._loop.engine.slot_length(slot)

    def bucket_for(self, prompt_len: int) -> int:
        """Host-side validation only — no broadcast needed."""
        return self._loop.engine.bucket_for(prompt_len)
