"""Distributed compute: device mesh, sharding rules, collectives.

The reference's only distributed axes were many independent provider
processes behind server routing plus per-provider connection caps
(SURVEY §2.3; reference src/provider.ts:38-40). Intra-provider parallelism
is net-new here and is expressed the TPU way: a `jax.sharding.Mesh`,
logical-axis PartitionSpecs on every parameter and activation, and XLA
inserting the collectives — never hand-written sends.
"""

from symmetry_tpu.parallel.mesh import MeshSpec, build_mesh
from symmetry_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_to_spec,
    shardings_for,
)

__all__ = [
    "MeshSpec",
    "build_mesh",
    "DEFAULT_RULES",
    "logical_to_spec",
    "shardings_for",
]
