"""symmetry-tpu: a TPU-native decentralized P2P AI-inference framework.

A ground-up rebuild of the capabilities of shlebbypops/symmetry (symmetry-cli,
/root/reference) — a P2P network where provider nodes join an encrypted swarm,
register with a routing server, and stream chat completions directly to peers —
with the inference engine itself implemented natively on TPU via JAX/XLA/Pallas
instead of proxying to an external GPU server.

Three roles (reference: readme.md Architecture diagram):
  - server   (symmetry_tpu.server):   session broker / model router / balancer
  - provider (symmetry_tpu.provider): model host; `tpu_native` engine or HTTP proxy
  - client   (symmetry_tpu.client):   requests a provider, streams completions
"""

__version__ = "0.1.0"
