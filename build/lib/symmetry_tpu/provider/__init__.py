from symmetry_tpu.provider.config import ConfigManager, TpuConfig
from symmetry_tpu.provider.provider import SymmetryProvider

__all__ = ["ConfigManager", "TpuConfig", "SymmetryProvider"]
