"""Provider CLI: `python -m symmetry_tpu.provider [-c path]`.

Parity with the reference bin (src/symmetry.ts:1-24): `-c/--config` defaults
to ~/.config/symmetry/provider.yaml; constructs the provider and serves until
SIGINT, then drains gracefully.
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from symmetry_tpu.provider.config import ConfigManager, default_config_path
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.utils.logging import logger


async def run(config_path: str) -> None:
    provider = SymmetryProvider(ConfigManager(config_path))
    await provider.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    logger.info("draining and shutting down…")
    await provider.stop()


def run_worker(config_path: str) -> None:
    """Non-rank-0 process of a multi-host provider: no networking — build
    the identical engine and mirror rank 0's jitted calls until stopped."""
    from symmetry_tpu.engine.engine import InferenceEngine
    from symmetry_tpu.parallel.multihost import CommandLoop

    config = ConfigManager(config_path)
    mh = config.tpu.multihost
    if not mh or mh.get("process_id", 0) == 0:
        raise SystemExit("--worker requires tpu.multihost with process_id > 0")
    engine = InferenceEngine.from_tpu_config(config.tpu)
    logger.info(f"worker rank {mh['process_id']} following rank 0…")
    CommandLoop(engine, is_coordinator=False).follow_forever()
    logger.info("worker stopped")


def main() -> None:
    parser = argparse.ArgumentParser(prog="symmetry-provider")
    parser.add_argument("-c", "--config", default=default_config_path(),
                        help="path to provider.yaml")
    parser.add_argument("--worker", action="store_true",
                        help="run as a multi-host worker rank (no network)")
    args = parser.parse_args()
    if args.worker:
        run_worker(args.config)
    else:
        asyncio.run(run(args.config))


if __name__ == "__main__":
    main()
