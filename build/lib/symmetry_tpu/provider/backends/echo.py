"""Echo backend: deterministic fake model for tests and protocol bring-up.

Streams the last user message back word-by-word as OpenAI-style SSE chunks —
the 'fake echo model' seam SURVEY §4 calls for, letting the full
client→server→provider path run with no TPU and no external server.
"""

from __future__ import annotations

import asyncio
import json
from typing import AsyncIterator

from symmetry_tpu.provider.backends.base import (
    InferenceBackend,
    InferenceRequest,
    StreamChunk,
)


class EchoBackend(InferenceBackend):
    name = "echo"

    def __init__(self, delay_s: float = 0.0) -> None:
        self._delay = delay_s

    async def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        last_user = ""
        for m in reversed(request.messages):
            if m.get("role") == "user":
                last_user = m.get("content", "")
                break
        words = last_user.split(" ") or [""]
        for i, word in enumerate(words):
            token = word if i == 0 else " " + word
            chunk = {
                "object": "chat.completion.chunk",
                "model": "echo",
                "choices": [{"index": 0, "delta": {"content": token}}],
            }
            yield StreamChunk(raw=f"data: {json.dumps(chunk)}", text=token)
            if self._delay:
                await asyncio.sleep(self._delay)
        yield StreamChunk(raw="data: [DONE]", text="", done=True)
