from symmetry_tpu.provider.backends.base import InferenceBackend, StreamChunk, get_backend
from symmetry_tpu.provider.backends.echo import EchoBackend

__all__ = ["InferenceBackend", "StreamChunk", "get_backend", "EchoBackend"]
