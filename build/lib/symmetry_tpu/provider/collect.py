"""Conversation data collection.

Parity with the reference's declared data collection
(src/provider.ts:277-297): when `dataCollectionEnabled`, each completed
conversation is written to `{path}/{peer_pubkey}-{conversation_index}.json`
containing the request messages plus the assembled completion. The flag is
announced to the server and surfaced to clients in providerDetails — providers
must declare collection openly (reference readme.md, Communication section).
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any

from symmetry_tpu.utils.logging import logger


class DataCollector:
    def __init__(self, base_path: str, enabled: bool) -> None:
        self.enabled = enabled
        self._base = os.path.expanduser(base_path)

    async def save(self, *, peer_key: str, conversation_index: int,
                   messages: list[dict[str, Any]], completion: str) -> str | None:
        if not self.enabled:
            return None
        os.makedirs(self._base, exist_ok=True)
        path = os.path.join(self._base, f"{peer_key}-{conversation_index}.json")
        payload = {
            "messages": messages + [{"role": "assistant", "content": completion}],
        }
        # Off the event loop: file IO must not stall the token pump.
        await asyncio.get_running_loop().run_in_executor(
            None, _write_json, path, payload
        )
        logger.debug(f"saved conversation to {path}")
        return path


def _write_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, ensure_ascii=False, indent=2)
    os.replace(tmp, path)
