"""Signed session tokens.

The reference defines `verifySession`/`sessionValid` keys (src/constants.ts:
17-18) with the verification logic living in the absent server sibling. We
implement sessions as *server-signed offline-verifiable tokens*: the server
signs {session_id, client_key, model, expiry} with its Ed25519 identity, and a
provider verifies the signature against the serverKey it already trusts from
its config — no provider→server round trip on the hot path. Clients can still
ask the server directly via `verifySession` → `sessionValid`.
"""

from __future__ import annotations

import json
import time
from typing import Any

from symmetry_tpu.identity import Identity


def _canonical(payload: dict[str, Any]) -> bytes:
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


def mint(server_identity: Identity, *, session_id: str, client_key: str,
         model_name: str, ttl_s: float = 3600.0) -> dict[str, Any]:
    payload = {
        "sessionId": session_id,
        "clientKey": client_key,
        "modelName": model_name,
        "expiresAt": time.time() + ttl_s,
    }
    return {"payload": payload, "signature": server_identity.sign(_canonical(payload)).hex()}


def verify(token: Any, server_key: bytes, *, client_key: str | None = None,
           model_name: str | None = None) -> dict[str, Any] | None:
    """Return the payload if the token is authentic and unexpired, else None."""
    if not isinstance(token, dict):
        return None
    payload, sig_hex = token.get("payload"), token.get("signature")
    if not isinstance(payload, dict) or not isinstance(sig_hex, str):
        return None
    try:
        sig = bytes.fromhex(sig_hex)
    except ValueError:
        return None
    if not Identity.verify(_canonical(payload), sig, server_key):
        return None
    if payload.get("expiresAt", 0) < time.time():
        return None
    if client_key is not None and payload.get("clientKey") != client_key:
        return None
    if model_name is not None and payload.get("modelName") != model_name:
        return None
    return payload
