"""Server CLI: `python -m symmetry_tpu.server` (or `symmetry-tpu-server`)."""

import asyncio

from symmetry_tpu.server.broker import main as _amain


def main() -> None:
    asyncio.run(_amain())


if __name__ == "__main__":
    main()
