from symmetry_tpu.server.registry import Registry
from symmetry_tpu.server.broker import SymmetryServer

__all__ = ["Registry", "SymmetryServer"]
