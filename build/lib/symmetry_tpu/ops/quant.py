"""Int8 weight quantization (BASELINE config 5: llama3-70b int8 TP).

Symmetric per-output-channel int8: for w [.., in, out], each output column
gets scale = max|column| / 127, q = round(w / scale). The matmul computes
(x @ q) * scale — exact w.r.t. per-column scaling, and the int8 weight
halves HBM traffic vs bf16, which is the decode bottleneck (weights are
re-read every step).

QuantizedTensor is a pytree, so quantized params stack under lax.scan,
shard with NamedShardings, and donate exactly like dense ones.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    q: jnp.ndarray      # int8, same shape as the dense weight
    scale: jnp.ndarray  # f32, weight shape minus the contraction dim


def quantize(w: jnp.ndarray, *, contract_axis: int = -2) -> QuantizedTensor:
    """Quantize a dense weight along its contraction (input) axis."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=jnp.squeeze(scale, axis=contract_axis))


def dequantize(qt: QuantizedTensor, dtype=jnp.float32,
               *, contract_axis: int = -2) -> jnp.ndarray:
    scale = jnp.expand_dims(qt.scale, contract_axis)
    return (qt.q.astype(jnp.float32) * scale).astype(dtype)


def qmatmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for dense arrays or QuantizedTensor ([in, out] contraction).

    Uses a mixed-precision dot with the int8 operand passed directly — no
    `astype` on the weight, so XLA never materializes a bf16 copy (for a
    128k-vocab head that copy alone is >1 GB). Accumulates f32, applies the
    per-column scales, casts back to the activation dtype.

    Measured alternative, not routed: the native s8×s8 MXU kernel
    (ops/qmm.py) is ~50% slower in-trunk at decode-sized M and exactly
    NEUTRAL at prefill-sized M (165.3 vs 167.6 ms per coalesced prefill
    group on-chip, despite winning isolated matmul microbenchmarks —
    prefill is not matmul-bound). Since W8A8 would add activation-quant
    noise for zero measured gain, the mixed dot serves both regimes.
    """
    if isinstance(w, QuantizedTensor):
        y = jax.lax.dot_general(
            x, w.q,
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * w.scale).astype(x.dtype)
    return x @ w


# One shared jitted quantizer: donating the dense original lets XLA reuse
# its buffer; both post-hoc tree quantization and quantized init go through
# this single definition.
quantize_jit = jax.jit(quantize, donate_argnums=(0,))


def quantize_tree(params: dict, keys: tuple[str, ...]) -> dict:
    """Quantize the named leaves of a params dict in place (donating the
    dense originals one at a time to bound peak memory)."""

    def visit(node):
        for name, child in list(node.items()):
            if isinstance(child, dict):
                visit(child)
            elif name in keys:
                node[name] = quantize_jit(child)

    visit(params)
    return params


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token-per-head symmetric int8 for KV cache entries.

    x [..., D] -> (q int8 [..., D], scale f32 [...]): one scale per leading
    index (token × kv-head), amax over the head_dim axis. At decode the
    cache read is the second-largest HBM stream after the weights; int8
    halves it, and the scale array is D× smaller than the payload.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("shape", "scale", "dtype", "quantized"))
def make_leaf(key, shape: tuple[int, ...], scale: float, dtype,
              quantized: bool = False):
    """Random-init one parameter leaf fully inside ONE compiled program:
    normal → scale → cast (→ quantize). Nothing full-precision survives the
    program, so peak memory per leaf is its fused temporaries — which is
    what makes 8B-scale quantized init fit on one chip."""
    w = (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)
    return quantize(w) if quantized else w
