"""TPU-first tensor ops: RoPE, attention, normalization, sampling, quant.

Everything here is a pure function over jax arrays with static shapes, safe
under `jax.jit` — the compute floor the reference never had (it proxied all
inference to an external HTTP server, reference: src/provider.ts:210-214).
"""

from symmetry_tpu.ops.rope import apply_rope, rope_cos_sin
from symmetry_tpu.ops.norm import rms_norm
from symmetry_tpu.ops.attention import gqa_attention
from symmetry_tpu.ops.sampling import sample_tokens

__all__ = [
    "apply_rope",
    "rope_cos_sin",
    "rms_norm",
    "gqa_attention",
    "sample_tokens",
]
