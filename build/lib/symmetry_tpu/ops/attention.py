"""Grouped-query attention over a static KV cache.

One attention routine serves both phases of serving:

  - prefill: q covers S new positions, cache already holds them (written
    before the call), mask is causal-by-absolute-position;
  - decode:  q covers 1 new position per slot, attends to everything the
    slot has written so far.

Masking is driven entirely by absolute positions, so the same jitted
computation handles ragged per-slot lengths in a continuous batch — the
shapes stay static (slots × max_seq) and the MXU sees one big batched
matmul rather than per-request loops (SURVEY §2.3: continuous batching is
the core net-new engine component).

The einsum groups query heads onto their KV head ([B, K, G, S, D]) instead of
materializing repeated K/V — with 8 q-heads per KV head (llama3-8b) that is
an 8x saving of HBM traffic on the cache read.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30  # large-but-finite: keeps softmax NaN-free for all-masked rows


def gqa_attention(
    q: jnp.ndarray,          # [B, S, n_q_heads, head_dim]
    k_cache: jnp.ndarray,    # [B, T, n_kv_heads, head_dim]  (T = cache capacity)
    v_cache: jnp.ndarray,    # [B, T, n_kv_heads, head_dim]
    q_positions: jnp.ndarray,  # [B, S] absolute position of each query token
    kv_length: jnp.ndarray,    # [B] number of valid cache entries per sample
    sliding_window: int | None = None,  # mistral-style local attention span
    k_scale: jnp.ndarray | None = None,  # [B, n_kv_heads, T] f32: int8 cache
    v_scale: jnp.ndarray | None = None,  # per-token-per-head dequant scales
) -> jnp.ndarray:
    """Returns [B, S, n_q_heads, head_dim] in q's dtype. Softmax in f32.

    With k_scale/v_scale set, k_cache/v_cache hold int8 payloads
    (ops/quant.py quantize_kv). Dequantization is folded into the existing
    contractions — k's scale multiplies the scores (k = q·s distributes over
    the dot product), v's scale multiplies the probabilities — so no bf16
    copy of the cache is ever materialized and the HBM read stays int8-wide.
    """
    B, S, n_q, D = q.shape
    T, n_kv = k_cache.shape[1], k_cache.shape[2]
    group = n_q // n_kv
    scale = D ** -0.5
    # HIGHEST forces multi-pass bf16 matmuls; with an int8 operand the
    # upcast is exact, so default precision loses nothing.
    prec = None if k_scale is not None else jax.lax.Precision.HIGHEST

    qg = q.reshape(B, S, n_kv, group, D)
    # scores: [B, n_kv, group, S, T]. f32 accumulation: bf16 qk products drift
    # visibly at long T, and the MXU accumulates in f32 natively anyway.
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k_cache,
        precision=prec,
        preferred_element_type=jnp.float32,
    )
    if k_scale is not None:
        scores = scores * k_scale[:, :, None, None, :]
    scores = scores * scale

    kv_pos = jnp.arange(T, dtype=jnp.int32)
    # key valid iff written (pos < kv_length) and causal (pos <= query pos)
    mask = (kv_pos[None, None, :] <= q_positions[..., None]) & (
        kv_pos[None, None, :] < kv_length[:, None, None]
    )  # [B, S, T]
    if sliding_window is not None:
        mask &= kv_pos[None, None, :] > q_positions[..., None] - sliding_window
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)

    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    if v_scale is not None:
        # Fold v's dequant scale into the probabilities (per key position) —
        # masked positions contribute 0 regardless of their garbage scale.
        probs = probs * v_scale[:, :, None, None, :]
    probs = probs.astype(q.dtype)

    out = jnp.einsum("bkgst,btkd->bskgd", probs, v_cache,
                     precision=prec,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return out.reshape(B, S, n_q, D)
