"""Rotary position embeddings (RoPE), HF-llama convention.

Uses the rotate-half layout (first half / second half pairing) so weights
loaded from HF llama/mistral checkpoints produce identical activations —
required because the north star loads HF safetensors directly (BASELINE.json).
Cos/sin are computed in float32 regardless of activation dtype; bf16 RoPE
phases drift noticeably past ~2k positions.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(
    positions: jnp.ndarray,  # [..., seq] int32 absolute positions
    head_dim: int,
    theta: float = 500000.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return (cos, sin) of shape [..., seq, head_dim], float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )  # [head_dim/2]
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., seq, hd/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., seq, head_dim]
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jnp.ndarray,          # [batch, seq, heads, head_dim]
    positions: jnp.ndarray,  # [batch, seq]
    theta: float = 500000.0,
) -> jnp.ndarray:
    """Rotate q or k by absolute position; returns x's dtype."""
    cos, sin = rope_cos_sin(positions, x.shape[-1], theta)
    # Broadcast over the heads axis: [batch, seq, 1, head_dim].
    cos, sin = cos[..., None, :], sin[..., None, :]
    xf = x.astype(jnp.float32)
    out = xf * cos + _rotate_half(xf) * sin
    return out.astype(x.dtype)
