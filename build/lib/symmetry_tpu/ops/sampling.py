"""Token sampling: greedy / temperature / top-k / top-p, batched and jittable.

Controls are per-slot arrays, not Python scalars, so one compiled sampler
serves a continuous batch where every request carries its own temperature
(InferenceRequest sampling fields, provider/backends/base.py). temperature==0
selects greedy via masking rather than control flow — no recompiles, no
data-dependent branching under jit.

Perf note: a full [B, V] sort at V=128k costs more than the decode matmuls
for small models, so sampling is restricted to the top `cap` logits via
`lax.top_k` (top-k at small k is a cheap partial reduction on TPU). Greedy
and any top_k <= cap are exact; top-p loses only the probability mass beyond
the top `cap` tokens (< 1e-3 for typical LM distributions at cap=64).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.attention import NEG_INF

SAMPLING_TOP_CAP = 64


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] float
    key: jax.Array,             # PRNG key — scalar, or [B] per-slot keys
    temperature: jnp.ndarray,   # [B] float; 0 => greedy
    top_p: jnp.ndarray,         # [B] float in (0, 1]; 1 => disabled
    top_k: jnp.ndarray,         # [B] int32; 0 => disabled
    cap: int = SAMPLING_TOP_CAP,
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    cap = min(cap, V)
    logits = logits.astype(jnp.float32)

    # Scale by temperature (guard 0 to keep the math finite; the greedy lane
    # is selected by the final where, not by this value).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    # Partial sort: [B, cap] descending, with original vocab indices.
    top_logits, top_idx = jax.lax.top_k(scaled, cap)

    ranks = jnp.arange(cap, dtype=jnp.int32)[None, :]
    # top-k: keep ranks < k (0 disables; anything beyond cap acts as cap).
    # Greedy (temperature == 0) is expressed as k = 1: with only rank 0
    # unmasked, the categorical below deterministically returns the argmax —
    # one select lane, no separate greedy branch.
    k = jnp.where(top_k > 0, top_k, cap)
    k = jnp.where(temperature > 0, k, 1)
    keep = ranks < k[:, None]
    # top-p: keep the smallest prefix whose probability mass reaches p.
    # (Mass is computed over the top-cap window — the tail beyond cap is
    # treated as zero, see module docstring.)
    probs = jax.nn.softmax(top_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept if the mass strictly before it is < p (always keeps rank 0)
    mass_before = cum - probs
    keep &= mass_before < top_p[:, None]

    masked = jnp.where(keep, top_logits, NEG_INF)
    if key.ndim:  # [B] per-slot keys: each row draws from its own stream
        choice_rank = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(key, masked)
    else:
        choice_rank = jax.random.categorical(key, masked, axis=-1)  # [B]
    sampled = jnp.take_along_axis(top_idx, choice_rank[:, None], axis=-1)[:, 0]
    return sampled.astype(jnp.int32)
