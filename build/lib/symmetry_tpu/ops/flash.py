"""Pallas flash attention for prefill (causal, GQA, ragged lengths).

Why: naive prefill attention materializes [heads, S, S] f32 scores — at the
2048 bucket that is ~0.5 GB per layer, and HBM traffic dominates. The flash
kernel streams K/V blocks through VMEM with the standard running-max /
running-sum rescaling, so score tiles never leave VMEM (online softmax).

Inputs arrive [B, S, H, D] (the model's layout) and are viewed [B, H, S, D]
for the kernel — TPU lowering needs the block's trailing dims to be the
tileable (S, D) pair. BlockSpec `None` dims pick the (batch, head)
coordinate per grid step and the GQA q→kv head mapping happens in the k/v
index_map (h // group), so repeated KV heads are never materialized.

Causality is block-skipped: the kv loop for query block `qi` runs only to
block qi, giving the ~2x FLOP saving of causal masking, with the partial
diagonal block masked by element positions. Ragged prompt lengths
(`seq_lens`, the padded-bucket contract of engine prefill) mask the same
way; fully-masked padded rows get a sum-guard instead of NaNs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30


def _flash_kernel(seqlen_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float,
                  block_q: int, block_k: int, window: int | None):
    qi = pl.program_id(2)
    seq_len = seqlen_ref[pl.program_id(0)]  # this batch row's true length

    q = q_ref[:].astype(jnp.float32) * scale  # [block_q, D]
    D = q.shape[-1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]  # [block_k, D]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        kv_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (kv_pos <= q_pos) & (kv_pos < seq_len)
        if window is not None:
            # mistral-style local attention: key within `window` of query
            mask &= kv_pos > q_pos - window
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    # Causal block skip: query block qi only sees kv blocks 0..qi; with a
    # sliding window, also skip blocks wholly OLDER than the window (the
    # oldest key any query in this block can see is qi*block_q - window+1).
    lo = 0
    if window is not None:
        lo = jnp.maximum(0, (qi * block_q - window + 1) // block_k)
    m, l, acc = jax.lax.fori_loop(lo, qi + 1, body, (m0, l0, acc0))
    # Padded rows (q_pos >= seq_len) are fully masked: l == 0. Guard the
    # division; their output is garbage by contract, but must not be NaN.
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "window", "interpret"))
def flash_prefill(
    q: jnp.ndarray,         # [B, S, H, D]
    k: jnp.ndarray,         # [B, S, K, D]
    v: jnp.ndarray,         # [B, S, K, D]
    seq_lens: jnp.ndarray,  # [B] int32 valid prompt lengths
    *,
    block_q: int = 128,
    block_k: int = 128,
    window: int | None = None,  # mistral-style sliding-window span
    interpret: bool = False,
) -> jnp.ndarray:
    """Causal self-attention over a fresh (cache-empty) padded prompt.

    Returns [B, S, H, D] in q's dtype. Requires S % block == 0 (buckets are
    chosen that way); positions are 0..S-1 (prefill-from-empty contract of
    engine prefill, engine.py). `window` restricts attention to the last
    `window` keys (sliding-window models); blocks wholly outside the
    window are skipped, making long-prompt prefill O(S·window).
    """
    B, S, H, D = q.shape
    K = k.shape[2]
    group = H // K
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"S={S} not a multiple of blocks {block_q}/{block_k}")
    scale = D ** -0.5

    # [B, S, H, D] -> [B, H, S, D]: trailing (S, D) dims are the TPU-tileable
    # pair; XLA fuses these transposes into the surrounding projections.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, S // block_q)
    kernel = functools.partial(_flash_kernel, scale=scale,
                               block_q=block_q, block_k=block_k,
                               window=window)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # seq_lens
            grid=grid,
            in_specs=[
                pl.BlockSpec((None, None, block_q, D),
                             lambda b, h, qi, sl: (b, h, qi, 0)),
                pl.BlockSpec((None, None, S, D),
                             lambda b, h, qi, sl: (b, h // group, 0, 0)),
                pl.BlockSpec((None, None, S, D),
                             lambda b, h, qi, sl: (b, h // group, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, None, block_q, D),
                                   lambda b, h, qi, sl: (b, h, qi, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=interpret,
    )(seq_lens, qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
