"""RMSNorm, float32 accumulation."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """LlamaRMSNorm semantics: normalize in f32, scale, cast back."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)
