"""Pallas ragged decode attention: per-slot length-aware KV block skipping.

The decode step is HBM-bound and the KV cache is its second-largest stream
(after the weights). The XLA einsum path must read the FULL [T] cache
capacity for every slot — masking discards the values but not the traffic —
and slicing the read at the XLA level measured slower than the full read
(it defeats the int8-dequant/matmul fusion; see the round-2 bench log).
This kernel reads only the occupied prefix of each slot's cache:

  - grid = (batch, T/block_t), T innermost; the k/v BlockSpec index_map
    CLAMPS the block index at the slot's last occupied block, so Pallas's
    revisit rule (a block whose index equals the previous iteration's is
    not re-fetched) skips the DMA for every unoccupied tail block. A slot
    at length 600 of an 8192-capacity cache streams 2 × 512-entry blocks,
    not 16 — fully dynamic, zero recompiles, per-slot.
  - The FULL [L, B, T, K, D] cache (native layout — reshaping it outside
    would force a relaid-out copy) is the kernel operand and the layer is
    a scalar-prefetch arg consumed by the index_map: layer selection is
    pure block addressing, never a materialized slice.
  - GQA without a head loop: ALL query heads contract against ALL kv heads
    in ONE [nq, K*block_t] MXU matmul; wrong-pair scores are masked to
    -inf BEFORE the online softmax, so they exp to exactly 0 and the
    output matmul [nq, K*block_t] @ [K*block_t, D] needs no selection —
    the zeros kill every cross-head term. 8x redundant MXU FLOPs, but the
    step is bandwidth-bound and this removes the per-head scalar work
    that otherwise dominates small grids.
  - Online softmax (running max/sum) accumulates in VMEM scratch across
    the T grid dimension; output is written on the final T iteration.
  - int8 caches (ops/quant.py quantize_kv): payload is read at 1 byte and
    dequantized in VMEM — k scales multiply the scores, v scales the
    probabilities, exactly like the XLA fallback (ops/attention.py).

Masking is by absolute position (kv_pos < kv_length), identical semantics
to ops/attention.py gqa_attention at decode (q position == length - 1).

Regime: the kernel wins when capacity is large relative to typical
occupancy (long-context serving — at 32k capacity the full-read einsum is
unserveable); at small capacities the einsum's fusion wins. supports()
encodes the measured crossover.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.0**30
DEFAULT_BLOCK_T = 512
# Below this cache capacity the XLA full-read einsum path measured faster
# than the kernel (grid overhead > saved bandwidth at 1-2k capacities).
MIN_CAPACITY = 4096


def _kernel(len_ref, layer_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, scale: float, block_t: int,
            n_kv: int, group: int, quantized: bool,
            window: int | None = None,
            ks_ref=None, vs_ref=None):
    del layer_ref  # consumed by the index_maps
    b = pl.program_id(0)
    t = pl.program_id(1)
    length = len_ref[b]
    n_blocks = (length + block_t - 1) // block_t
    # Sliding window: keys below (length - window) are dead — blocks fully
    # below it are skipped (their DMA too, via the index_map clamp; for
    # t < first the fetched block belongs to `first` and must not be
    # processed under this t, hence the compute gate below).
    first = (jnp.maximum(length - window, 0) // block_t
             if window is not None else 0)
    nq, D = q_ref.shape
    KB = n_kv * block_t

    @pl.when(t == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when((t >= first) & (t < n_blocks))
    def _():
        q = q_ref[:].astype(jnp.float32) * scale          # [nq, D]
        # Dequant scales multiply the K/V blocks in 3-D BEFORE flattening
        # (same algebra as scaling scores/probs; Mosaic cannot shape-cast
        # a per-position scale vector onto the flattened score lanes).
        # Scale blocks arrive [K, block_t] (position-minor layout).
        kb = k_ref[:].astype(jnp.float32)                 # [block_t, K, D]
        if quantized:
            kb = kb * ks_ref[:].T[:, :, None]
        # [block_t, K, D] -> [block_t*K, D]: leading-dim merge, layout-free.
        # Flat row j holds (t_in_block = j // K, head = j % K).
        s = jax.lax.dot_general(
            q, kb.reshape(KB, D),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [nq, K*block_t]
        col = jax.lax.broadcasted_iota(jnp.int32, (nq, KB), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (nq, KB), 0)
        kv_pos = t * block_t + col // n_kv
        # own-head (query row h ↔ kv head h // group) AND in-length
        keep = ((col % n_kv) == (row // group)) & (kv_pos < length)
        if window is not None:
            # decode q position == length - 1: window floor is length - w
            keep &= kv_pos >= length - window
        s = jnp.where(keep, s, NEG_INF)

        m_old = m_scr[:, 0:1]
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                            # 0 at masked cols
        corr = jnp.exp(m_old - m_new)
        l_scr[:, 0:1] = l_scr[:, 0:1] * corr + jnp.sum(p, -1, keepdims=True)
        m_scr[:, 0:1] = m_new
        vb = v_ref[:].astype(jnp.float32)                 # [block_t, K, D]
        if quantized:
            vb = vb * vs_ref[:].T[:, :, None]
        acc_scr[:, :D] = acc_scr[:, :D] * corr + jax.lax.dot_general(
            p, vb.reshape(KB, D),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        # Empty / fully-masked rows have l == 0: guard the divide (their
        # output is garbage by contract, but must not be NaN).
        o_ref[:] = (acc_scr[:, :D]
                    / jnp.maximum(l_scr[:, 0:1], 1e-30)).astype(o_ref.dtype)


def _quant_kernel(len_ref, layer_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                  o_ref, m_scr, l_scr, acc_scr, **kw):
    _kernel(len_ref, layer_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, quantized=True,
            ks_ref=ks_ref, vs_ref=vs_ref, **kw)


def supports(config, cache_capacity: int, backend: str) -> bool:
    """Static gate for routing decode attention through the kernel.

    Long-context capacities only: below MIN_CAPACITY the XLA einsum path
    measured as fast or faster (round-3 re-measure with fetch-fenced
    timing: kernel 33.6 vs einsum 32.6 ms full-trunk at 640 — the step
    there is convert-throughput-bound, not KV-traffic-bound, so block
    skipping buys nothing). Sliding-window models route through the
    kernel too: the window bounds the block range per slot (mistral at
    8k capacity / 4k window reads half the blocks)."""
    D = config.dim_per_head
    return (D % 128 == 0
            and backend == "tpu"
            and cache_capacity >= MIN_CAPACITY
            # decode_attention auto-picks a block from (512, 256, 128, 64),
            # so any 64-multiple capacity tiles.
            and cache_capacity % 64 == 0)


@functools.partial(jax.jit,
                   static_argnames=("block_t", "window", "interpret"))
def decode_attention(
    q: jnp.ndarray,           # [B, n_q_heads, D] (single decode position)
    k_cache: jnp.ndarray,     # [L, B, T, K, D] FULL cache (bf16/f32 or int8)
    v_cache: jnp.ndarray,
    layer: jnp.ndarray,       # scalar int32: which layer's cache to read
    kv_length: jnp.ndarray,   # [B] int32 valid entries (incl. current token)
    k_scale: jnp.ndarray | None = None,  # [L, B, K, T] f32 (int8 caches;
    v_scale: jnp.ndarray | None = None,  # position minor — tile-friendly)
    *,
    block_t: int = DEFAULT_BLOCK_T,
    window: int | None = None,  # sliding-window span (mistral); bounds the
                                # per-slot block range below AND above
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns [B, n_q_heads, D] in q's dtype."""
    L, B, T, K, D = k_cache.shape
    nq = q.shape[1]
    group = nq // K
    block_t = min(block_t, T)
    if T % block_t:
        # Auto-pick the largest standard block that tiles the capacity
        # (e.g. 640 → 128); callers then never need capacity-aware sizing.
        for cand in (256, 128, 64):
            if cand < block_t and T % cand == 0:
                block_t = cand
                break
        else:
            raise ValueError(f"cache capacity {T} has no usable block size")
    n_t = T // block_t
    scale = D ** -0.5
    quantized = k_scale is not None

    layer_arr = jnp.reshape(layer, (1,)).astype(jnp.int32)

    def clamp_t(b, t, len_ref, layer_ref):
        # Clamp into the live block range for this slot: above the last
        # occupied block, and (windowed models) below the first block the
        # window can still see. Out-of-range iterations repeat a boundary
        # index, so Pallas's revisit rule skips their DMAs; the kernel's
        # compute gate skips their math.
        last = jnp.maximum((len_ref[b] + block_t - 1) // block_t - 1, 0)
        t_eff = jnp.minimum(t, last)
        if window is not None:
            first = jnp.maximum(len_ref[b] - window, 0) // block_t
            t_eff = jnp.maximum(t_eff, first)
        return layer_ref[0], b, t_eff, 0, 0

    q_spec = pl.BlockSpec((None, nq, D), lambda b, t, lr, yr: (b, 0, 0))
    kv_spec = pl.BlockSpec((None, None, block_t, K, D), clamp_t)
    out_spec = pl.BlockSpec((None, nq, D), lambda b, t, lr, yr: (b, 0, 0))
    scratch = [
        pltpu.VMEM((nq, 128), jnp.float32),  # running max (col 0)
        pltpu.VMEM((nq, 128), jnp.float32),  # running denom (col 0)
        pltpu.VMEM((nq, max(D, 128)), jnp.float32),  # output accumulator
    ]
    common = dict(scale=scale, block_t=block_t, n_kv=K, group=group,
                  window=window)

    if quantized:
        def clamp_t_scale(b, t, len_ref, layer_ref):
            lay, bb, tt, _, _ = clamp_t(b, t, len_ref, layer_ref)
            return lay, bb, 0, tt

        sc_spec = pl.BlockSpec((None, None, K, block_t), clamp_t_scale)
        kernel = functools.partial(_quant_kernel, **common)
        in_specs = [q_spec, kv_spec, kv_spec, sc_spec, sc_spec]
        args = (kv_length, layer_arr, q, k_cache, v_cache, k_scale, v_scale)
    else:
        kernel = functools.partial(_kernel, quantized=False, **common)
        in_specs = [q_spec, kv_spec, kv_spec]
        args = (kv_length, layer_arr, q, k_cache, v_cache)

    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # kv_length, layer
            grid=(B, n_t),
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((B, nq, D), q.dtype),
        interpret=interpret,
    )(*args)
