"""Native int8 MXU matmul (W8A8) — measured, NOT routed (see below).

The regime matters (all numbers measured on this v5e, fetch-fenced,
carry-dependent loops — tools/probe_s8_mxu.py, tools/bisect_decode.py):

  - DECODE (M ≈ slot count, ~128 rows): bandwidth-bound. Every int8 form
    is convert-throughput-limited; this kernel measured ~50% SLOWER than
    the XLA mixed dot in the full trunk (48.5 vs 32.1 ms). Decode stays
    on ops/quant.qmatmul's mixed dot.
  - PREFILL (M ≥ ~256 token rows): the kernel's s8×s8 MXU tiles measure
    ~172 TFLOP/s in ISOLATION at M=512 (vs the convert-limited mixed
    dot), but routed into the real prefill path the end-to-end group
    time is identical (165.3 vs 167.6 ms) — prefill is not matmul-bound.
    Since W8A8 adds per-row activation-quant noise for zero measured
    gain, it is NOT routed; the mixed dot serves both regimes.

Kept as a correct, tested building block (tests/test_qmm.py pins the
arithmetic against a bit-exact integer reference in interpret mode) and
as the measurement record — a future TPU generation or a genuinely
matmul-bound workload may flip the verdict. The activation is quantized
dynamically per row to int8; the s32 tile products are rescaled in the
kernel epilogue by (row activation scale × per-output-channel weight
scale).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes measured on v5e (tools/probe_s8_mxu.py, M=512): smaller bn
# keeps more N-blocks for the grid, which generalizes better to narrow
# layers; (512, 1024) performs comparably at wide shapes.
BLOCK_N = 256
BLOCK_K = 512
MIN_ROWS = 32  # below this the MXU is mostly idle


def _kernel(x_ref, w_ref, xs_ref, ws_ref, o_ref, acc_scr, *, n_k: int,
            out_dtype):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        x_ref[:], w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == n_k - 1)
    def _():
        # epilogue: s32 -> f32, row scale × column scale, cast out
        o_ref[:] = (acc_scr[:].astype(jnp.float32)
                    * xs_ref[:] * ws_ref[:]).astype(out_dtype)


def _pick_block(dim: int, prefer: int) -> int | None:
    for cand in (prefer, 512, 256, 128, 64):
        if cand <= prefer and dim % cand == 0:
            return cand
    return None


def supports(m: int, k: int, n: int, backend: str) -> bool:
    """Static gate for the w8a8 kernel (shapes tileable, MXU-worthy M)."""
    return (backend == "tpu"
            and m >= MIN_ROWS
            and _pick_block(k, BLOCK_K) is not None
            and _pick_block(n, BLOCK_N) is not None)


def quantize_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8: x [M, K] -> (q [M, K] s8, scale [M, 1] f32)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def w8a8_matmul(
    x: jnp.ndarray,        # [M, K] float (bf16/f32)
    wq: jnp.ndarray,       # [K, N] int8
    w_scale: jnp.ndarray,  # [N] f32 per-output-channel
    *,
    out_dtype=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ dequant(wq) with the activation quantized per row to int8 and
    the product computed as native s8×s8 → s32 MXU tiles."""
    M, K = x.shape
    Kw, N = wq.shape
    assert K == Kw, (K, Kw)
    out_dtype = out_dtype or x.dtype
    bk = _pick_block(K, BLOCK_K)
    bn = _pick_block(N, BLOCK_N)
    if bk is None or bn is None:
        raise ValueError(f"untileable w8a8 shape K={K} N={N}")
    n_k = K // bk

    xq, xs = quantize_rows(x)
    ws = w_scale.astype(jnp.float32).reshape(1, N)

    return pl.pallas_call(
        functools.partial(_kernel, n_k=n_k, out_dtype=out_dtype),
        grid=(N // bn, n_k),
        in_specs=[
            pl.BlockSpec((M, bk), lambda n, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
            pl.BlockSpec((M, 1), lambda n, k: (0, 0)),
            pl.BlockSpec((1, bn), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((M, bn), jnp.int32)],
        interpret=interpret,
    )(xq, wq, xs, ws)
