from symmetry_tpu.network.peer import Peer

__all__ = ["Peer"]
