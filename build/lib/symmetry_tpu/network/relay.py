"""Server-spliced relay: the NAT fallback when even punching fails.

The reference inherits relaying from the hyperdht stack (SURVEY §2.2:
"NAT holepunching, relaying"). Here the Symmetry server plays the relay:

    client ──(Noise)── server ──(Noise)── provider
              RELAY_DATA splice (broker)

Each end wraps its encrypted channel TO THE SERVER in a RelayedConnection
— a transport.base.Connection whose frames travel as RELAY_DATA messages —
and then runs the normal client↔provider Noise handshake THROUGH it
(network/peer.py with the provider key pinned). The server forwards only
ciphertext: it can deny service, but cannot read or impersonate either
end (the reference's relay has the same property via hypercore
end-to-end encryption).

Flow (keys in protocol/keys.py):
  client   → server : relayConnect {providerKey}
  server   → provider(control) : relayOpen {relayId}
  provider → server (new conn) : relayAccept {relayId}
  server   → both  : relayReady {relayId}
  both     ↔ server: relayData {frame b64}  (spliced)
  either   → server: relayClose / disconnect → teardown both ends
"""

from __future__ import annotations

import asyncio
import base64
from typing import Any

from symmetry_tpu.protocol.keys import MessageKey
from symmetry_tpu.transport.base import Connection
from symmetry_tpu.utils.logging import logger


class RelayedConnection(Connection):
    """A Connection tunneled in RELAY_DATA messages over a Peer channel.

    Takes EXCLUSIVE ownership of the underlying peer's read loop: after
    construction nothing else may recv on that peer."""

    def __init__(self, peer: Any, relay_id: str) -> None:
        self._peer = peer
        self._relay_id = relay_id
        self._inbox: asyncio.Queue[bytes | None] = asyncio.Queue()
        self._closed = False
        self._reader = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        try:
            async for msg in self._peer:
                if msg.key == MessageKey.RELAY_DATA:
                    frame = (msg.data or {}).get("frame", "")
                    try:
                        self._inbox.put_nowait(
                            base64.b64decode(frame, validate=True))
                    except (ValueError, TypeError):
                        continue
                elif msg.key == MessageKey.RELAY_CLOSE:
                    break
                # anything else on a spliced channel is a stray; ignore
        except (ConnectionError, OSError) as exc:
            logger.debug(f"relay pump ended: {exc}")
        finally:
            self._inbox.put_nowait(None)

    async def send(self, frame: bytes) -> None:
        if self._closed:
            raise ConnectionError("relayed connection closed")
        await self._peer.send(
            MessageKey.RELAY_DATA,
            {"id": self._relay_id,
             "frame": base64.b64encode(frame).decode()})

    async def recv(self) -> bytes | None:
        if self._closed:
            return None
        frame = await self._inbox.get()
        if frame is None:
            self._closed = True
        return frame

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            await self._peer.send(MessageKey.RELAY_CLOSE,
                                  {"id": self._relay_id})
        except (ConnectionError, OSError):
            pass
        self._reader.cancel()
        await self._peer.close()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def remote_address(self) -> str:
        return f"relay://{self._relay_id}"


async def await_ready(peer: Any, relay_id: str | None = None,
                      timeout: float = 10.0) -> str:
    """Consume messages until relayReady; returns the relay id.

    With `relay_id` set (provider side) only that id completes the wait;
    with None (client side, which learns the id FROM relayReady) the
    first ready wins. The one shared implementation keeps both roles'
    refusal handling identical."""
    async def _wait() -> str:
        async for msg in peer:
            if msg.key == MessageKey.RELAY_READY:
                got = str((msg.data or {}).get("id", ""))
                if relay_id is None or got == relay_id:
                    return got
            elif msg.key == MessageKey.RELAY_CLOSE:
                raise ConnectionError("relay refused")
            elif msg.key == MessageKey.INFERENCE_ERROR:
                raise ConnectionError(
                    (msg.data or {}).get("error", "relay failed"))
        raise ConnectionError("server closed during relay setup")

    return await asyncio.wait_for(_wait(), timeout)
