"""Message envelope: `{key, data}` JSON objects.

Same envelope shape as the reference (`ProviderMessage<T>`, src/types.ts:23-26;
`createMessage`, src/utils.ts:12-14), but carried inside length-framed (and,
post-handshake, encrypted) frames instead of raw unframed JSON writes — the
reference relies on each `peer.write` arriving as exactly one `data` event
(src/provider.ts:110-115,174-179), which TCP does not guarantee.

Trace context convention: an `inference` frame's data may carry
`"traceId"` (client-minted, utils/trace.new_trace_id) — providers thread
it through the backend and host pipe so every component's spans correlate
on one timeline — and the provider's stream-start reply carries `"tMono"`
(its CLOCK_MONOTONIC read at send) so the client can estimate the
provider-clock offset for the merged Perfetto export. Both fields are
optional: peers that ignore them interoperate unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from symmetry_tpu.protocol.keys import normalize_key
from symmetry_tpu.utils.json import dumps, safe_parse_json


@dataclass(slots=True)
class Message:
    key: str
    data: Any = None

    def encode(self) -> bytes:
        obj: dict[str, Any] = {"key": self.key}
        if self.data is not None:
            obj["data"] = self.data
        return dumps(obj)


def create_message(key: str, data: Any = None) -> bytes:
    """Encode a `{key, data}` envelope (reference: src/utils.ts:12-14)."""
    return Message(key, data).encode()


def parse_message(raw: bytes | str | None) -> Message | None:
    """Decode an envelope; None on malformed input (never raises on bad peers)."""
    obj = safe_parse_json(raw)
    if not isinstance(obj, dict) or "key" not in obj or not isinstance(obj["key"], str):
        return None
    return Message(key=normalize_key(obj["key"]), data=obj.get("data"))
