"""Wire-protocol message keys.

Superset of the reference's `serverMessageKeys` vocabulary
(reference: src/constants.ts:3-20), which is the de-facto protocol spec between
server, provider, and client. The reference's misspelled `conectionSize` is kept
as an accepted alias for interop.

New keys (marked TPU) extend the protocol for the native engine: structured
token streaming, usage metrics, and graceful drain.
"""

from __future__ import annotations


class MessageKey:
    # --- reference vocabulary (src/constants.ts:3-20) ---
    CHALLENGE = "challenge"
    CONNECTION_SIZE = "connectionSize"
    CONNECTION_SIZE_ALIAS = "conectionSize"  # sic — reference spelling, accepted on ingress
    HEARTBEAT = "heartbeat"
    INFERENCE = "inference"
    INFERENCE_ENDED = "inferenceEnded"
    JOIN = "join"
    JOIN_ACK = "joinAck"
    LEAVE = "leave"
    NEW_CONVERSATION = "newConversation"
    PING = "ping"
    PONG = "pong"
    PROVIDER_DETAILS = "providerDetails"
    REPORT_COMPLETION = "reportCompletion"
    REQUEST_PROVIDER = "requestProvider"
    SESSION_VALID = "sessionValid"
    VERIFY_SESSION = "verifySession"

    # --- TPU-native extensions ---
    CHALLENGE_RESPONSE = "challengeResponse"  # signed challenge reply (both directions)
    TOKEN_CHUNK = "tokenChunk"                # structured streamed tokens (engine-native)
    INFERENCE_ERROR = "inferenceError"        # structured mid-stream failure
    INFERENCE_CANCEL = "inferenceCancel"      # client aborts one in-flight
                                              # request by its requestId
    DRAIN = "drain"                           # graceful shutdown: stop accepting, finish in-flight
    METRICS = "metrics"                       # provider → server load metrics (tok/s, queue
                                              # depth); client ⇄ provider stats probe — the
                                              # reply carries the stats snapshot plus a
                                              # "metrics" block of tier-labeled registry
                                              # snapshots (utils/metrics.py), so symtop and
                                              # the swarm path scrape without an open port
    PROVIDER_LIST = "providerList"            # server → client available models
    TRACE = "trace"                           # client ⇄ provider: merged span-ring
                                              # snapshot (client, provider, host,
                                              # scheduler components) for the
                                              # Perfetto timeline export
    PROFILE = "profileCapture"                # client ⇄ provider: trigger one
                                              # bounded on-device jax.profiler
                                              # capture (HostOp.PROFILE under-
                                              # neath); the reply carries the
                                              # trace-artifact path or an error

    # --- relay (NAT fallback: server splices client↔provider, payload
    #     stays end-to-end Noise-encrypted — the reference gets this leg
    #     from hyperdht relaying; network/relay.py) ---
    RELAY_CONNECT = "relayConnect"            # client → server {providerKey}
    RELAY_OPEN = "relayOpen"                  # server → provider {relayId}
    RELAY_ACCEPT = "relayAccept"              # provider → server {relayId}
    RELAY_READY = "relayReady"                # server → both ends
    RELAY_DATA = "relayData"                  # spliced opaque frames
    RELAY_CLOSE = "relayClose"                # either end / server teardown


class HostOp:
    """Engine-host pipe ops — the `{"op": ...}` JSON-lines protocol
    between the provider backend and its engine-host subprocess(es)
    (spec: engine/host.py docstring; disagg forwarding:
    engine/disagg/broker.py).

    One registry on purpose: producers and consumers both import these
    constants, and the symlint wire-contract checker (tools/symlint.py)
    fails CI on any raw op literal or any op produced without a
    consumer — a renamed op used to mean a silently-dropped frame and
    a hung stream, not an error."""

    # --- commands: provider/broker → host stdin ---
    SUBMIT = "submit"       # new request (messages, sampling, deadline…)
    ADOPT = "adopt"         # decode role: adopt a handed-off KV frame
    CANCEL = "cancel"       # abort one in-flight request by id
    CLOCK = "clock"         # clock-offset handshake probe (echoed back)
    TRACE = "trace"         # span-ring snapshot request (echoed back)
    STATS = "stats"         # scheduler/emit counters probe (echoed
                            # back). The reply doubles as the pool
                            # gossip carrier: a host with a live radix
                            # cache attaches a "prefix_summary" rider
                            # (bounded block digests + depth histogram,
                            # engine/prefix_cache.py summary()) that
                            # the pool router harvests off its
                            # heartbeat probes for cache-affine
                            # placement — no new op, no extra wire
                            # round-trip. Symmetrically, SUBMIT carries
                            # an optional "ledger" rider ({member,
                            # epoch}) telling the prefill host which
                            # decode member's shipped-block ledger the
                            # handoff should be keyed against. The same
                            # reply is the autoscaler's sensor feed:
                            # "queue_depth" and the symprof "devprof"
                            # block (device_s_total) are differenced
                            # per heartbeat into the per-tier load and
                            # measured-M:N-ratio inputs of
                            # engine/disagg/autoscale.py.
    METRICS = "metrics"     # metrics-registry snapshot probe (echoed
                            # back with the host process's registry
                            # families + its tier role; the provider
                            # merges them tier-labeled into its own
                            # exposition and the MessageKey.METRICS
                            # reply — the swarm path needs no open port)
    PROFILE = "profile"     # on-demand jax.profiler capture: the host
                            # runs a bounded device trace off the
                            # serve loop and echoes the artifact path
                            # (or an error) back — triggered by the
                            # provider wire op, SIGUSR1, or the SLO
                            # burn-rate breach hook (utils/devprof.py)
    SHUTDOWN = "shutdown"   # graceful drain + exit

    # --- frames: host stdout → provider ---
    READY = "ready"         # warmup done, model/slots/geometry attached
    EVENT = "event"         # one token event (legacy single-event frame)
    EVENTS = "events"       # batched per-block token events (hot path)
    HANDOFF = "handoff"     # prefill role: serialized KV prefix frame


HOST_OPS = frozenset(
    v for k, v in vars(HostOp).items()
    if not k.startswith("_") and isinstance(v, str)
)


class LinkOp:
    """Cross-machine handoff-link ops — the `{"op": ...}` envelope headers
    of the disagg network transport (engine/disagg/net.py) between a
    decode-tier node (the tpu_native provider) and a prefill-tier node
    (engine/disagg/node.py), carried over the transport/ stack.

    Same registry discipline as HostOp: producers and consumers both
    import these constants and the symlint wire-contract checker scans
    the link-protocol group (LINK_GROUP in analysis/wire_contract.py),
    so a renamed link op fails CI instead of silently stranding a
    handoff mid-wire. Where a link op FORWARDS a host op (submit,
    cancel, stats, trace), the value is deliberately the same string —
    the node can splice the payload straight onto the host pipe."""

    # --- control (both directions) ---
    HELLO = "hello"         # link handshake: version, role, credit
                            # window, node identity ("node") — the pool
                            # router's join/announce signal
    CLOCK = "clock"         # clock-offset probe (echoed with "t"), same
                            # NTP-midpoint protocol as the host pipe
    PING = "ping"           # link keepalive probe (pool heartbeat; the
                            # decode side drops a silent link and lets
                            # the reconnect loop own recovery)
    PONG = "pong"           # keepalive reply (echoes the ping's "t")

    # --- decode node → prefill node ---
    SUBMIT = "submit"       # forwarded host submit op (payload = JSON line)
    CANCEL = "cancel"       # forwarded host cancel op
    STATS = "stats"         # stats probe: node replies host stats + link stats
    TRACE = "trace"         # trace probe: node replies host span rings
    CREDIT = "credit"       # flow control: return n consumed chunk bytes
    ACK = "ack"             # handoff transfer fully reassembled + forwarded
    NAK = "nak"             # transfer failed integrity — sender retransmits

    # --- prefill node → decode node ---
    BEGIN = "begin"         # handoff transfer start: id, xfer, len, meta
    CHUNK = "chunk"         # one payload chunk: id, xfer, seq + raw bytes
    END = "end"             # transfer complete: id, xfer, crc
    FAIL = "fail"           # handoff abandoned (retries exhausted / host
                            # death) — the decode node sheds the request
    EVENT = "event"         # prefill-tier terminal event (tokenization /
                            # admission error, deadline shed) forwarded
    DRAIN = "drain"         # node announces deliberate drain: no new
                            # placements; in-flight work finishes
    LEAVE = "leave"         # node announces departure (drain complete /
                            # shutdown) — membership churn, not a fault


LINK_OPS = frozenset(
    v for k, v in vars(LinkOp).items()
    if not k.startswith("_") and isinstance(v, str)
)


SERVER_MESSAGE_KEYS = frozenset(
    v for k, v in vars(MessageKey).items() if not k.startswith("_")
)


def normalize_key(key: str) -> str:
    """Map reference-compat aliases to canonical keys."""
    if key == MessageKey.CONNECTION_SIZE_ALIAS:
        return MessageKey.CONNECTION_SIZE
    return key
