"""Wire-protocol message keys.

Superset of the reference's `serverMessageKeys` vocabulary
(reference: src/constants.ts:3-20), which is the de-facto protocol spec between
server, provider, and client. The reference's misspelled `conectionSize` is kept
as an accepted alias for interop.

New keys (marked TPU) extend the protocol for the native engine: structured
token streaming, usage metrics, and graceful drain.
"""

from __future__ import annotations


class MessageKey:
    # --- reference vocabulary (src/constants.ts:3-20) ---
    CHALLENGE = "challenge"
    CONNECTION_SIZE = "connectionSize"
    CONNECTION_SIZE_ALIAS = "conectionSize"  # sic — reference spelling, accepted on ingress
    HEARTBEAT = "heartbeat"
    INFERENCE = "inference"
    INFERENCE_ENDED = "inferenceEnded"
    JOIN = "join"
    JOIN_ACK = "joinAck"
    LEAVE = "leave"
    NEW_CONVERSATION = "newConversation"
    PING = "ping"
    PONG = "pong"
    PROVIDER_DETAILS = "providerDetails"
    REPORT_COMPLETION = "reportCompletion"
    REQUEST_PROVIDER = "requestProvider"
    SESSION_VALID = "sessionValid"
    VERIFY_SESSION = "verifySession"

    # --- TPU-native extensions ---
    CHALLENGE_RESPONSE = "challengeResponse"  # signed challenge reply (both directions)
    TOKEN_CHUNK = "tokenChunk"                # structured streamed tokens (engine-native)
    INFERENCE_ERROR = "inferenceError"        # structured mid-stream failure
    INFERENCE_CANCEL = "inferenceCancel"      # client aborts one in-flight
                                              # request by its requestId
    DRAIN = "drain"                           # graceful shutdown: stop accepting, finish in-flight
    METRICS = "metrics"                       # provider → server load metrics (tok/s, queue depth)
    PROVIDER_LIST = "providerList"            # server → client available models
    TRACE = "trace"                           # client ⇄ provider: merged span-ring
                                              # snapshot (client, provider, host,
                                              # scheduler components) for the
                                              # Perfetto timeline export

    # --- relay (NAT fallback: server splices client↔provider, payload
    #     stays end-to-end Noise-encrypted — the reference gets this leg
    #     from hyperdht relaying; network/relay.py) ---
    RELAY_CONNECT = "relayConnect"            # client → server {providerKey}
    RELAY_OPEN = "relayOpen"                  # server → provider {relayId}
    RELAY_ACCEPT = "relayAccept"              # provider → server {relayId}
    RELAY_READY = "relayReady"                # server → both ends
    RELAY_DATA = "relayData"                  # spliced opaque frames
    RELAY_CLOSE = "relayClose"                # either end / server teardown


SERVER_MESSAGE_KEYS = frozenset(
    v for k, v in vars(MessageKey).items() if not k.startswith("_")
)


def normalize_key(key: str) -> str:
    """Map reference-compat aliases to canonical keys."""
    if key == MessageKey.CONNECTION_SIZE_ALIAS:
        return MessageKey.CONNECTION_SIZE
    return key
