"""The Symmetry client: request a provider from the server, stream completions.

The reference's client was refactored out of the repo (the test still imports
`SymmetryClient` from ../src/client — __test__/cli.test.ts:1 — which no longer
exists; SURVEY §0.1). This is its re-creation against our wire protocol:

    client = SymmetryClient(identity, transport)
    details = await client.request_provider(server_addr, server_key, "llama3:8b")
    async with await client.connect(details) as session:
        async for delta in session.chat([{"role": "user", "content": "hi"}]):
            print(delta, end="")
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from symmetry_tpu.identity import Identity
from symmetry_tpu.network.peer import Peer
from symmetry_tpu.protocol.keys import MessageKey
from symmetry_tpu.provider.backends.proxy import (
    get_chat_data_from_provider,
    safe_parse_stream_response,
)
from symmetry_tpu.transport.base import Transport
from symmetry_tpu.utils.logging import logger
from symmetry_tpu.utils.trace import Tracer, new_trace_id


class ClientError(RuntimeError):
    pass


class ProviderGoneError(ClientError):
    """The assigned provider died or closed mid-stream — the retryable
    failure class. Request-level errors (bad messages, invalid session)
    stay plain ClientError: replaying those on another provider would
    burn the pool on a deterministically-bad request."""


class ProviderDiedMidStreamError(ProviderGoneError):
    """The provider died AFTER streaming part of the completion. Carries
    everything a resume needs: the text deltas the client already holds
    (`emitted_text` — authoritative: TCP ordering guarantees it is
    exactly the prefix the provider relayed) and the emitted TOKEN count
    when the wire managed to stamp one (`emitted_tokens`; None when the
    connection just dropped — the resume path then lets the serving host
    re-derive the count from the text). chat_failover turns this into a
    `resume` request instead of regenerating from token 0."""

    def __init__(self, message: str, emitted_text: str = "",
                 emitted_tokens: int | None = None) -> None:
        super().__init__(message)
        self.emitted_text = emitted_text
        self.emitted_tokens = emitted_tokens


class ProviderBusyError(ClientError):
    """The provider shed the request before serving it (its backlog is
    over queue_limit) — retryable on ANOTHER provider: nothing streamed,
    and the request itself is fine. Carries the provider's reported
    queue depth/limit for backoff decisions."""

    def __init__(self, message: str, queue_depth: int | None = None,
                 queue_limit: int | None = None,
                 draining: bool = False) -> None:
        super().__init__(message)
        self.queue_depth = queue_depth
        self.queue_limit = queue_limit
        # A draining provider is shutting down for good: fail over NOW
        # and don't come back — unlike a backlog shed, no backoff round
        # will ever find it admitting again.
        self.draining = draining


class ProviderRestartingError(ProviderBusyError):
    """The provider's engine host crashed/wedged mid-service and its
    supervisor is respawning it — retryable on ANOTHER provider now, and
    on this one after ~retry_after_s. Subclasses ProviderBusyError so it
    joins the existing busy-shed failover + backoff machinery (the
    provider is transiently unable, not dead — it must not be excluded
    from the pool as a corpse)."""

    def __init__(self, message: str, retry_after_s: float | None = None,
                 emitted_text: str = "",
                 emitted_tokens: int | None = None, **kw) -> None:
        super().__init__(message, **kw)
        self.retry_after_s = retry_after_s
        # Mid-stream restarting sheds carry what already streamed, same
        # contract as ProviderDiedMidStreamError: the structured shed
        # frame stamps the provider's EXACT relayed-token count (what
        # this client holds — TCP ordering), so a seeded resume restores
        # its RNG lane to the right position. The engine host's journal
        # rides separately as emittedEngine when it exceeds the relayed
        # count (tokens that died on the pipe — lost work, not resume
        # state).
        self.emitted_text = emitted_text
        self.emitted_tokens = emitted_tokens


class ResumeRefusedError(ClientError):
    """The provider refused to RESUME (its backend regenerates from
    scratch — splicing would duplicate the completion). The request
    itself is fine: chat_failover falls back to one from-scratch
    restart instead of failing the call."""


class DeadlineExceededError(ClientError):
    """The request's end-to-end deadline_s expired before it was served.
    Deliberately NOT retryable (plain ClientError lineage): nobody is
    waiting for the answer anymore, so replaying it on another provider
    would burn pool capacity for a result that gets thrown away."""


def busy_retry_backoff(queue_depth: int | None, queue_limit: int | None,
                       round_idx: int = 0,
                       retry_after_s: float | None = None,
                       rand=random.random) -> float:
    """Backoff before a busy-shed retry round.

    Base wait scales with how deep the shedding provider's backlog was
    relative to its limit (bounded at 2 s so a huge depth never becomes
    a stall of our own) and doubles per retry round. The ±50% JITTER is
    the point: a burst of clients shed together would otherwise sleep
    the same formula and re-stampede the recovering provider in
    lockstep. The provider's retry_after hint (a restarting provider
    knows its respawn backoff better than we do) is ADDED UNDER the
    jittered wait, never multiplied into it: retrying before the hint is
    guaranteed to be shed again, and jittering the hint downward would
    do exactly that — so everyone waits at least the hint, desynchronized
    beyond it.

    When the shed DID carry a hint, the per-round doubling is clamped to
    the round-0 base: the hint already encodes how long the provider
    needs (its own respawn backoff), and doubling our base on top of it
    would amplify a restarting provider's honest estimate into a wait
    that grows with OUR retry count — a resume round after a mid-stream
    crash must honor the hint, not punish it (the doubling exists for
    hint-LESS busy sheds, where depth is the only signal we have)."""
    depth = queue_depth or 0
    limit = queue_limit or 0
    over = depth / limit if limit > 0 else 1.0
    # Round-0 base is bounded at 2 s (a huge reported depth must never
    # become a stall of our own) and the per-round doubling has its own
    # ceiling (×16) for the same reason — a caller asking for many retry
    # rounds gets persistence, not quarter-hour sleeps.
    doubling = 1 if retry_after_s is not None else (
        2 ** min(max(0, round_idx), 4))
    base = min(2.0, 0.25 * (1.0 + over)) * doubling
    wait = base * (0.5 + rand())
    if retry_after_s is not None:
        wait += float(retry_after_s)
    return wait


@dataclass(slots=True)
class ProviderDetails:
    peer_key: str
    address: str | None
    model_name: str
    session_token: dict | None = None
    session_id: str | None = None
    data_collection: bool = False
    provider_dialect: str = "openai"  # chunk format hint for delta extraction
    raw: dict = field(default_factory=dict)


@dataclass(slots=True)
class ChatRestart:
    """Failover marker: a new provider took over and generation restarted —
    everything streamed before this event must be discarded.
    `discarded_tokens` is the emitted-token count of the voided partial
    (None when no attempt stamped one) — the wasted-work numerator the
    chaos bench compares against the resume path's."""

    attempt: int
    provider_key: str
    discarded_tokens: int | None = None


@dataclass(slots=True)
class ChatResume:
    """Failover marker: a new provider took over and generation RESUMED
    from the last token the client received — everything streamed before
    this event is still valid, and the deltas that follow splice onto it
    (token-identical to an uninterrupted run for greedy and seeded
    sampling). `resumed_tokens` is how many already-streamed tokens the
    resume skipped regenerating — the wasted-work the resume path saved."""

    attempt: int
    provider_key: str
    resumed_tokens: int | None = None


class ProviderSession:
    """A live connection to one provider.

    Requests are MULTIPLEXED: every chat carries a requestId the provider
    echoes on each stream message, and one reader task routes messages to
    per-request queues — so concurrent chat() calls on a single session
    interleave correctly (the round-2 verdict's per-session-serialization
    limit, rooted in the reference's id-less wire, src/provider.ts:195).
    An abandoned stream is cancelled provider-side (inferenceCancel) and
    its stragglers dropped, instead of desyncing the whole session."""

    def __init__(self, peer: Peer, details: ProviderDetails,
                 tracer: Tracer | None = None) -> None:
        self._peer = peer
        self._details = details
        # Usage of the last completed chat, from inferenceEnded:
        # {"tokens": N, "chunks": M} (engine backends count exact
        # tokens), plus — when the provider runs with tpu.ledger on —
        # a "costs" block: the request's symledger attribution
        # (device_s{phase}, wasted_s{reason}, queue_s, emit_s, saved_s)
        # as the scheduler booked it. See last_costs.
        self.last_usage: dict | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        self._stats_q: asyncio.Queue = asyncio.Queue()
        self._stats_lock = asyncio.Lock()
        self._trace_q: asyncio.Queue = asyncio.Queue()
        self._trace_lock = asyncio.Lock()
        self._profile_q: asyncio.Queue = asyncio.Queue()
        self._profile_lock = asyncio.Lock()
        self._reader: asyncio.Task | None = None
        self._closed = False
        # Client-side spans (chat round trip, first delta) land in the
        # owning SymmetryClient's tracer so one merge covers every
        # session. The provider clock offset (provider monotonic − ours)
        # is estimated from the stream-start marker's tMono stamp
        # bracketed by our send/receive stamps — a piggybacked handshake;
        # the lowest-RTT estimate seen so far wins.
        self.tracer = tracer if tracer is not None else Tracer()
        self.clock_offset: float | None = None
        self._clock_rtt = float("inf")

    @property
    def last_costs(self) -> dict | None:
        """The last completed chat's symledger cost block — what the
        request actually cost in attributed device time, as stamped on
        its end frame. None when the provider serves with tpu.ledger
        off (or no chat has completed on this session)."""
        usage = self.last_usage
        costs = usage.get("costs") if isinstance(usage, dict) else None
        return costs if isinstance(costs, dict) else None

    def _ensure_reader(self) -> None:
        if self._reader is None:
            self._reader = asyncio.get_running_loop().create_task(
                self._read_loop())

    async def _read_loop(self) -> None:
        """Single reader: routes stream messages by requestId."""
        try:
            while True:
                msg = await self._peer.recv()
                if msg is None:
                    break
                data = msg.data or {}
                if msg.key == MessageKey.METRICS:
                    self._stats_q.put_nowait(data)
                    continue
                if msg.key == MessageKey.TRACE:
                    self._trace_q.put_nowait(data)
                    continue
                if msg.key == MessageKey.PROFILE:
                    self._profile_q.put_nowait(data)
                    continue
                req_id = str(data.get("requestId", ""))
                q = self._queues.get(req_id)
                if q is None and not req_id and self._queues:
                    if len(self._queues) == 1:
                        # version skew: a pre-multiplexing provider echoes
                        # no requestId — with exactly one request in
                        # flight the stream is unambiguous, so route it
                        # there instead of hanging the caller forever
                        q = next(iter(self._queues.values()))
                    else:
                        # multiple requests in flight against an id-less
                        # provider: attribution is impossible — fail them
                        # all loudly rather than dropping chunks and
                        # deadlocking every caller on queue.get()
                        logger.error(
                            "provider echoes no requestId but multiple "
                            "requests are in flight; failing them — use "
                            "one chat at a time with this provider")
                        for pending_q in self._queues.values():
                            pending_q.put_nowait(None)
                        self._queues.clear()
                        continue
                if q is not None:
                    q.put_nowait(msg)
                elif msg.key in (MessageKey.INFERENCE,
                                 MessageKey.TOKEN_CHUNK,
                                 MessageKey.INFERENCE_ENDED,
                                 MessageKey.INFERENCE_ERROR):
                    # straggler of an abandoned (cancelled) request — drop
                    logger.debug(f"client: dropping stray {msg.key!r} "
                                 f"for request {req_id or '?'}")
                else:
                    logger.debug(f"client: ignoring key {msg.key!r}")
        finally:
            self._closed = True
            for q in self._queues.values():
                q.put_nowait(None)  # wire gone
            self._stats_q.put_nowait(None)
            self._trace_q.put_nowait(None)
            self._profile_q.put_nowait(None)

    async def __aenter__(self) -> "ProviderSession":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def new_conversation(self) -> None:
        await self._peer.send(MessageKey.NEW_CONVERSATION)

    async def chat(
        self,
        messages: list[dict[str, str]],
        *,
        max_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        top_k: int | None = None,
        seed: int | None = None,
        speculative: bool | None = None,
        trace_id: str | None = None,
        deadline_s: float | None = None,
        resume_text: str | None = None,
        resume_tokens: int | None = None,
    ) -> AsyncIterator[str]:
        """Send one inference request; yield text deltas as they stream.
        Safe to call concurrently on one session (requestId multiplexing).

        `resume_text` marks this chat as a RESUME of an interrupted
        stream: the provider continues generation from the end of that
        text (conditioning on prompt + resume_text through its prefix
        cache) instead of regenerating it, and yields only the
        continuation. `resume_tokens` is the emitted-token count the
        text represents (from the shed's stamped journal count) — it
        positions a seeded request's RNG lane; None lets the serving
        host re-derive it from the text. A mid-stream failure raises
        ProviderDiedMidStreamError / ProviderRestartingError carrying
        the deltas yielded so far, so the caller can resume elsewhere.

        Every chat carries a trace id (minted here unless the caller
        brings one): the provider threads it through its backend and the
        engine host, so one id keys the request's spans in every
        component of the merged timeline (session.trace / export).

        `deadline_s` is the end-to-end deadline: it threads provider →
        engine, and a request whose deadline expires while still queued
        is shed (DeadlineExceededError, non-retryable) instead of being
        prefilled for nobody."""
        import uuid as _uuid

        self._check_usable()
        req_id = _uuid.uuid4().hex[:16]
        trace_id = trace_id or new_trace_id()
        payload: dict[str, Any] = {"key": "inference", "messages": messages,
                                   "requestId": req_id,
                                   "traceId": trace_id}
        if self._details.session_token is not None:
            payload["sessionToken"] = self._details.session_token
        for k, v in (("max_tokens", max_tokens), ("temperature", temperature),
                     ("top_p", top_p), ("top_k", top_k), ("seed", seed),
                     ("speculative", speculative),
                     ("deadline_s", deadline_s)):
            if v is not None:
                payload[k] = v
        if resume_text is not None:
            payload["resume"] = {"text": resume_text,
                                 **({"tokens": int(resume_tokens)}
                                    if resume_tokens is not None else {})}
        self._ensure_reader()
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[req_id] = queue
        ended = False
        t_send = time.monotonic()
        t_first: float | None = None
        n_deltas = 0
        # Everything yielded so far, for the resume path: a mid-stream
        # death's error carries it, and the caller splices a continuation
        # onto it instead of discarding the work.
        emitted_parts: list[str] = []

        def _mid_stream(exc: ClientError) -> ClientError:
            """Attach the emitted state to a mid-stream retryable. A
            pre-first-delta failure stays the plain class (nothing to
            resume)."""
            if not emitted_parts:
                return exc
            if isinstance(exc, ProviderRestartingError):
                exc.emitted_text = "".join(emitted_parts)
                return exc
            if isinstance(exc, ProviderGoneError):
                return ProviderDiedMidStreamError(
                    str(exc), emitted_text="".join(emitted_parts))
            return exc

        try:
            await self._peer.send(MessageKey.INFERENCE, payload)
            dialect = self._details.provider_dialect
            while True:
                msg = await queue.get()
                if msg is None:
                    ended = True  # wire gone; nothing left to misroute
                    raise _mid_stream(ProviderGoneError(
                        "provider closed connection mid-stream"))
                if msg.key == MessageKey.INFERENCE:
                    # stream-start marker; carries the backend dialect —
                    # and the provider's monotonic stamp, bracketed by our
                    # send/receive stamps for the clock-offset estimate.
                    data = msg.data or {}
                    dialect = data.get("provider", dialect)
                    t_mono = data.get("tMono")
                    if isinstance(t_mono, (int, float)):
                        now = time.monotonic()
                        rtt = now - t_send
                        if rtt < self._clock_rtt:
                            self._clock_rtt = rtt
                            self.clock_offset = (
                                float(t_mono) - (t_send + now) / 2.0)
                elif msg.key == MessageKey.TOKEN_CHUNK:
                    raw = (msg.data or {}).get("raw", "")
                    parsed = safe_parse_stream_response(raw)
                    if parsed is None:
                        continue
                    delta = get_chat_data_from_provider(dialect, parsed)
                    if delta:
                        if t_first is None:
                            t_first = time.monotonic()
                            self.tracer.record(
                                "client_ttft", t_send, t_first - t_send,
                                request_id=req_id, trace_id=trace_id)
                        n_deltas += 1
                        emitted_parts.append(delta)
                        yield delta
                elif msg.key == MessageKey.INFERENCE_ENDED:
                    ended = True
                    data = msg.data or {}
                    if data.get("cancelled"):
                        # provider-side cancellation (shutdown/drain): a
                        # truncated stream must look like provider death —
                        # retryable — not a normal completion
                        raise _mid_stream(ProviderGoneError(
                            "provider cancelled the stream"))
                    self.last_usage = data
                    return
                elif msg.key == MessageKey.INFERENCE_ERROR:
                    ended = True
                    data = msg.data or {}
                    if data.get("resumeUnsupported"):
                        # Structured resume refusal (proxy backend):
                        # typed so failover can fall back to a restart
                        # without guessing from the message text.
                        raise ResumeRefusedError(
                            data.get("error", "resume not supported"))
                    if data.get("expired"):
                        # Deadline shed: terminal, not retryable — nobody
                        # is waiting for this answer anymore.
                        raise DeadlineExceededError(
                            data.get("error", "deadline expired"))
                    if data.get("restarting"):
                        # Engine-host crash/wedge, supervisor respawning:
                        # retryable — fail over now, optionally come back
                        # after retryAfterS. Mid-stream sheds stamp the
                        # relayed-token count ("emitted", journal-fed) so
                        # the resume can restore a seeded RNG lane.
                        emitted = data.get("emitted")
                        raise _mid_stream(ProviderRestartingError(
                            data.get("error", "provider restarting"),
                            retry_after_s=data.get("retryAfterS"),
                            emitted_tokens=(int(emitted)
                                            if isinstance(emitted, int)
                                            else None),
                            queue_depth=data.get("queueDepth"),
                            queue_limit=data.get("queueLimit")))
                    if data.get("busy"):
                        # Structured shed (provider over queue_limit, or
                        # draining): distinguishable so failover retries
                        # elsewhere instead of treating it as a bad
                        # request.
                        raise ProviderBusyError(
                            data.get("error", "provider busy"),
                            queue_depth=data.get("queueDepth"),
                            queue_limit=data.get("queueLimit"),
                            draining=bool(data.get("draining")))
                    raise ClientError(
                        data.get("error", "inference failed"))
        finally:
            self.tracer.record("client_request", t_send,
                               time.monotonic() - t_send,
                               request_id=req_id, trace_id=trace_id,
                               deltas=n_deltas, completed=ended)
            self._queues.pop(req_id, None)
            if not ended and not self._peer.closed:
                # Abandoned mid-stream: cancel provider-side (frees the
                # engine slot); any stragglers are dropped by the reader.
                try:
                    await self._peer.send(MessageKey.INFERENCE_CANCEL,
                                          {"requestId": req_id})
                except (ConnectionError, OSError):
                    pass

    def _check_usable(self) -> None:
        if self._closed:
            raise ProviderGoneError("session is closed")

    async def chat_text(self, messages: list[dict[str, str]], **kw) -> str:
        return "".join([d async for d in self.chat(messages, **kw)])

    async def stats(self) -> dict:
        """Query the provider's serving metrics snapshot (tok/s, TTFT/e2e
        percentiles, occupancy).

        Runs through the shared reader; concurrent with chats, serialized
        only against other stats calls (metrics replies carry no id)."""
        self._check_usable()
        self._ensure_reader()
        async with self._stats_lock:
            # The reader may have exited while we awaited the lock — its
            # single None sentinel would be eaten by the drain below and
            # the get() would hang forever on a closed session.
            self._check_usable()
            # a previously-timed-out stats() may have left its reply
            # queued; drain so this call gets ITS OWN snapshot
            while not self._stats_q.empty():
                if self._stats_q.get_nowait() is None:
                    raise ProviderGoneError("provider closed connection")
            await self._peer.send(MessageKey.METRICS)
            try:
                data = await asyncio.wait_for(self._stats_q.get(), 30.0)
            except asyncio.TimeoutError:
                raise ProviderGoneError(
                    "no stats reply within 30s") from None
            if data is None:
                raise ProviderGoneError("provider closed during stats query")
            return data

    async def trace(self) -> dict:
        """Query the provider's merged span-ring snapshot (provider +
        host + scheduler components, stamps on the provider's clock).
        Same reader/serialization discipline as stats()."""
        self._check_usable()
        self._ensure_reader()
        async with self._trace_lock:
            self._check_usable()
            while not self._trace_q.empty():
                if self._trace_q.get_nowait() is None:
                    raise ProviderGoneError("provider closed connection")
            await self._peer.send(MessageKey.TRACE)
            try:
                data = await asyncio.wait_for(self._trace_q.get(), 30.0)
            except asyncio.TimeoutError:
                raise ProviderGoneError(
                    "no trace reply within 30s") from None
            if data is None:
                raise ProviderGoneError("provider closed during trace query")
            return data

    async def capture_profile(self, duration_s: float = 2.0) -> dict:
        """Trigger one bounded on-device jax.profiler capture on the
        provider's engine and await the result: {"path": <trace dir>}
        on success, {"error": ...} otherwise (no device backend, or a
        capture already in progress). The reply arrives only after the
        capture window closes — the timeout budgets for it. Same
        reader/serialization discipline as stats()/trace()."""
        self._check_usable()
        self._ensure_reader()
        async with self._profile_lock:
            self._check_usable()
            while not self._profile_q.empty():
                if self._profile_q.get_nowait() is None:
                    raise ProviderGoneError("provider closed connection")
            await self._peer.send(MessageKey.PROFILE,
                                  {"durationS": float(duration_s)})
            try:
                # Budget the capture window PLUS the profiler's cold
                # init (the process's first capture can take tens of
                # seconds) and the provider's own probe margin.
                data = await asyncio.wait_for(self._profile_q.get(),
                                              duration_s + 150.0)
            except asyncio.TimeoutError:
                raise ProviderGoneError(
                    "no profile reply within the capture window") from None
            if data is None:
                raise ProviderGoneError(
                    "provider closed during profile capture")
            return data

    async def trace_components(self) -> list[dict]:
        """Provider-side components reconciled onto THIS client's clock:
        every component's clock_offset_s gains the session's measured
        provider offset, plus the client's own span ring at offset 0 —
        ready for utils.trace.export_perfetto."""
        payload = await self.trace()
        off = self.clock_offset or 0.0
        comps = []
        for comp in payload.get("components") or []:
            if isinstance(comp, dict):
                comps.append({**comp, "clock_offset_s":
                              float(comp.get("clock_offset_s", 0.0)) + off})
        comps.append(self.tracer.component("client"))
        return comps

    async def close(self) -> None:
        self._closed = True
        if self._reader is not None:
            self._reader.cancel()
        if not self._peer.closed:
            try:
                await self._peer.send(MessageKey.LEAVE)
            except (ConnectionError, OSError):
                pass
        await self._peer.close()


class SymmetryClient:
    def __init__(self, identity: Identity | None = None,
                 transport: Transport | None = None) -> None:
        self.identity = identity or Identity.generate()
        if transport is None:
            from symmetry_tpu.transport.tcp import TcpTransport

            transport = TcpTransport()  # CLI passes transport_for(server)
        self._transport = transport
        # One span ring for all this client's sessions: chat round trips
        # and first-delta spans, merged with provider-side components by
        # export_trace / ProviderSession.trace_components.
        self.tracer = Tracer()

    async def export_trace(self, session: "ProviderSession") -> dict:
        """One request's (or session's) end-to-end timeline as Chrome
        trace-event JSON: the provider's merged components (provider,
        host, scheduler — reconciled through the measured clock offsets)
        plus this client's spans. Write it to a file and load it in
        Perfetto (ui.perfetto.dev) or chrome://tracing."""
        from symmetry_tpu.utils.trace import export_perfetto

        return export_perfetto(await session.trace_components())

    async def request_provider(
        self, server_address: str, server_key: bytes, model_name: str | None = None,
        timeout: float = 10.0, exclude: list[str] | None = None,
    ) -> ProviderDetails:
        """Ask the server for a provider assignment (requestProvider →
        providerDetails, reference keys src/constants.ts:16,14). `exclude`
        lists peer keys the server must not hand back (failover re-request
        after a provider died)."""
        conn = await self._transport.dial(server_address)
        peer = await Peer.connect(
            conn, self.identity, initiator=True, expected_remote_key=server_key
        )
        try:
            req: dict[str, Any] = {"modelName": model_name}
            if exclude:
                req["excludePeers"] = list(exclude)
            await peer.send(MessageKey.REQUEST_PROVIDER, req)
            msg = await asyncio.wait_for(peer.recv(), timeout)
            if msg is None or msg.key != MessageKey.PROVIDER_DETAILS:
                raise ClientError(f"unexpected server reply: {msg and msg.key}")
            data = msg.data or {}
            if "error" in data:
                raise ClientError(data["error"])
            prov = data.get("provider") or {}
            return ProviderDetails(
                peer_key=prov.get("peerKey", ""),
                address=prov.get("address"),
                model_name=prov.get("modelName", model_name or ""),
                session_token=data.get("sessionToken"),
                session_id=data.get("sessionId"),
                data_collection=bool(prov.get("dataCollectionEnabled", False)),
                raw=data,
            )
        finally:
            await peer.close()

    async def list_models(self, server_address: str, server_key: bytes,
                          timeout: float = 10.0) -> list[dict]:
        conn = await self._transport.dial(server_address)
        peer = await Peer.connect(
            conn, self.identity, initiator=True, expected_remote_key=server_key
        )
        try:
            await peer.send(MessageKey.PROVIDER_LIST)
            msg = await asyncio.wait_for(peer.recv(), timeout)
            return (msg.data or {}).get("models", []) if msg else []
        finally:
            await peer.close()

    async def chat_failover(
        self,
        server_address: str,
        server_key: bytes,
        model_name: str,
        messages: list[dict[str, str]],
        *,
        attempts: int = 3,
        busy_retry_rounds: int = 1,
        resume: bool = True,
        **chat_kw,
    ) -> AsyncIterator[str | "ChatRestart" | "ChatResume"]:
        """Streaming chat with provider failover and mid-stream RESUME.

        If the assigned provider dies MID-STREAM (crash, wedge, link cut,
        pool-member loss — any retryable shed after the first delta), the
        next attempt issues a `resume` request instead of regenerating:
        the new provider continues from the last token this client
        received (conditioning on prompt + received text through its
        radix prefix cache), a ChatResume sentinel is yielded, and the
        continuation deltas SPLICE onto what was already yielded —
        token-identical to an uninterrupted run for greedy and seeded
        sampling. `resume=False` restores the old discard-and-restart
        behavior. A provider that refuses the resume (proxy backend, or
        a history that outgrew its prefill buckets) triggers ONE
        fallback to a from-scratch restart.

        If the assigned provider dies before anything streamed, the
        server is asked for a FRESH provider (the dead one excluded — its
        sessions were invalidated server-side) and generation restarts.
        A restart yields a ChatRestart sentinel first: text streamed from
        the dead provider is void and consumers must discard it.
        chat_text_failover does both bookkeepings for you.

        Busy-shed backoff: when busy (or restarting) sheds exhausted the
        pool — the providers are healthy, just over their backlog bound
        or mid-respawn, a transient — the busy providers are un-excluded
        and up to `busy_retry_rounds` extra rounds run, each after a
        JITTERED backoff (busy_retry_backoff: sized from the shed reply's
        queue_depth/queue_limit, doubled per round, on top of the
        provider's retryAfterS hint, ±50% jitter so synchronized clients
        don't re-stampede a recovering provider in lockstep).
        `busy_retry_rounds=0` disables the retry entirely.
        Genuinely-dead providers stay excluded throughout.

        `deadline_s` (via chat_kw) is END-TO-END across all attempts:
        each retry carries only the time remaining, and the loop raises
        DeadlineExceededError itself once the budget is spent — failing
        over with a reset deadline would admit work nobody awaits.
        """
        dead: list[str] = []
        busy: list[str] = []
        # Resume state: every delta yielded so far (still-valid text once
        # a resume splices onto it) and its emitted-token count (None
        # once any failed attempt couldn't stamp one — the serving host
        # then re-derives the count from the text). `resuming` arms the
        # NEXT attempt as a resume instead of a restart.
        acc_parts: list[str] = []
        acc_tokens: int | None = 0
        resuming = False
        last_exc: Exception | None = None
        # Tracked separately from last_exc: pool exhaustion surfaces as a
        # plain ClientError from request_provider AFTER the busy shed, so
        # gating the retry on last_exc would skip it exactly when the
        # sheds emptied the pool — the case the backoff exists for.
        last_busy: ProviderBusyError | None = None
        n_tries = 0
        # End-to-end deadline across ALL attempts: passing the original
        # deadline_s verbatim on each retry would re-anchor the window
        # at every provider's receipt, turning a 2 s budget into 2 s per
        # hop — the caller stopped waiting, but the pool keeps admitting.
        deadline_s = chat_kw.pop("deadline_s", None)
        t_deadline0 = time.monotonic()
        total_rounds = 1 + max(0, busy_retry_rounds)
        for round_idx in range(total_rounds):
            pool_exhausted = False
            for _ in range(attempts):
                kw = chat_kw
                if deadline_s is not None:
                    remaining = deadline_s - (time.monotonic()
                                              - t_deadline0)
                    if remaining <= 0:
                        raise DeadlineExceededError(
                            f"deadline_s={deadline_s} spent after "
                            f"{n_tries} provider attempt(s)")
                    kw = {**chat_kw, "deadline_s": remaining}
                try:
                    details = await self.request_provider(
                        server_address, server_key, model_name,
                        exclude=dead + busy)
                except ClientError as exc:
                    last_exc = exc
                    pool_exhausted = True
                    break  # no provider left to fail over to
                if n_tries > 0:
                    if resuming:
                        yield ChatResume(attempt=n_tries,
                                         provider_key=details.peer_key,
                                         resumed_tokens=acc_tokens)
                    else:
                        # From-scratch restart: the partial text is void
                        # (its token count rides the sentinel — the
                        # wasted work the resume path exists to save).
                        discarded = (acc_tokens if acc_parts else None)
                        acc_parts.clear()
                        acc_tokens = 0
                        yield ChatRestart(attempt=n_tries,
                                          provider_key=details.peer_key,
                                          discarded_tokens=discarded)
                n_tries += 1
                try:
                    # relay_via: a NAT-only provider (direct dial fails,
                    # the server splice works) is serviceable, not dead
                    session = await self.connect(
                        details, relay_via=(server_address, server_key))
                except (ClientError, ConnectionError, OSError) as exc:
                    last_exc = exc
                    if details.peer_key:
                        dead.append(details.peer_key)
                    continue
                before = len(acc_parts)
                try:
                    ckw = kw
                    if resuming:
                        ckw = {**kw, "resume_text": "".join(acc_parts),
                               "resume_tokens": acc_tokens}
                    async for delta in session.chat(messages, **ckw):
                        acc_parts.append(delta)
                        yield delta
                    return
                except DeadlineExceededError:
                    # Terminal by contract — never converted to a
                    # restart, resumed, or retried.
                    raise
                except (ProviderGoneError, ProviderBusyError,
                        ConnectionError, OSError) as exc:
                    # Provider-death AND busy-shed failures fail over (a
                    # shed provider is healthy but over its backlog bound
                    # — this request is excluded from it, not the
                    # provider from the pool). A request-level
                    # ClientError (bad messages, rejected params)
                    # propagates: replaying it elsewhere would fail
                    # identically while blacklisting healthy providers.
                    last_exc = exc
                    if (isinstance(exc, ProviderBusyError)
                            and not getattr(exc, "draining", False)):
                        # Tracked even for a keyless provider row (no
                        # exclusion possible): the shed itself is what
                        # makes the end-of-round backoff retry eligible.
                        last_busy = exc
                        if details.peer_key:
                            busy.append(details.peer_key)
                    elif details.peer_key:
                        # Dead — or DRAINING: a shutting-down provider
                        # will never admit again, so it is excluded like
                        # a corpse and earns no backoff retry round.
                        dead.append(details.peer_key)
                    if len(acc_parts) > before:
                        # Streamed something this attempt: fold its
                        # stamped token count into the running total (a
                        # missing stamp poisons the count to None — the
                        # host re-derives it from the text).
                        et = getattr(exc, "emitted_tokens", None)
                        acc_tokens = (acc_tokens + int(et)
                                      if acc_tokens is not None
                                      and et is not None else None)
                    # Everything yielded so far (this attempt's deltas
                    # included) is still valid — the next attempt
                    # CONTINUES it. The mid-stream provider is already
                    # excluded above (dead or busy), so the immediate
                    # resume round lands elsewhere when a peer exists.
                    resuming = resume and bool(acc_parts)
                except ClientError as exc:
                    # A failed RESUME attempt falls back ONCE to a plain
                    # restart — the next attempt regenerates from token
                    # 0 after a ChatRestart. Two flavors: the structured
                    # refusal (ResumeRefusedError — proxy backend,
                    # expected) and any other resume-time error (e.g.
                    # prompt+history beyond the host's prefill buckets,
                    # which only exists because of the resume — the
                    # original messages already streamed fine once, so
                    # this is not a deterministically-bad request).
                    # A non-resume ClientError keeps the old contract
                    # and propagates.
                    if not resuming:
                        raise
                    if isinstance(exc, ResumeRefusedError):
                        logger.info(f"resume refused ({exc}); falling "
                                    f"back to a from-scratch restart")
                    else:
                        logger.warning(
                            f"resume attempt failed ({exc}); falling "
                            f"back to a from-scratch restart")
                    last_exc = exc
                    resuming = False
                finally:
                    await session.close()
            # Retry only when busy sheds actually ended the round: the
            # pool ran dry with sheds among the exclusions, or the final
            # attempt itself was shed. A round that merely PASSED THROUGH
            # a busy provider before dying on dead ones gets no bonus
            # attempts beyond the caller's budget.
            if (round_idx + 1 < total_rounds and last_busy is not None
                    and (pool_exhausted
                         or isinstance(last_exc, ProviderBusyError))):
                # The backlog that shed us drains at roughly one slot
                # rotation; the jittered backoff (see busy_retry_backoff)
                # spreads the returning herd over it.
                backoff = busy_retry_backoff(
                    last_busy.queue_depth, last_busy.queue_limit,
                    round_idx=round_idx,
                    retry_after_s=getattr(last_busy, "retry_after_s",
                                          None))
                if deadline_s is not None:
                    remaining = deadline_s - (time.monotonic()
                                              - t_deadline0)
                    if remaining <= backoff:
                        # Sleeping through the rest of the budget just to
                        # raise on the next attempt is strictly worse
                        # than raising now.
                        raise DeadlineExceededError(
                            f"deadline_s={deadline_s}: {remaining:.2f}s "
                            f"left, retry backoff {backoff:.2f}s would "
                            f"overrun it")
                logger.debug(
                    f"pool exhausted on busy sheds "
                    f"(depth={last_busy.queue_depth} "
                    f"limit={last_busy.queue_limit}); retry round "
                    f"{round_idx + 1}/{total_rounds - 1} in {backoff:.2f}s")
                await asyncio.sleep(backoff)
                busy.clear()
                # Each retry round must earn the NEXT one with fresh
                # sheds — a stale shed from round 0 must not keep the
                # loop alive after a round of pure dial failures.
                last_busy = None
                continue
            break
        raise ClientError(
            f"chat failed after {n_tries or attempts} provider "
            f"attempt(s): {last_exc}")

    async def chat_text_failover(self, server_address: str, server_key: bytes,
                                 model_name: str,
                                 messages: list[dict[str, str]],
                                 **kw) -> str:
        """chat_failover collected to a final string (restart- and
        resume-aware: a ChatResume keeps the partial text — the
        continuation splices onto it; a ChatRestart voids it)."""
        parts: list[str] = []
        async for item in self.chat_failover(server_address, server_key,
                                             model_name, messages, **kw):
            if isinstance(item, ChatRestart):
                parts.clear()  # the dead provider's partial text is void
            elif isinstance(item, ChatResume):
                pass  # spliced continuation: everything so far is valid
            else:
                parts.append(item)
        return "".join(parts)

    async def connect(self, details: ProviderDetails,
                      *, relay_via: tuple[str, bytes] | None = None
                      ) -> ProviderSession:
        """Dial a provider directly, pinning its key from providerDetails.

        With `relay_via=(server_address, server_key)`, a failed direct
        dial falls back to the server-spliced relay (network/relay.py) —
        the reference's behind-NAT reachability leg."""
        if not details.address and relay_via is None:
            raise ClientError("provider has no dialable address")
        expected = bytes.fromhex(details.peer_key) if details.peer_key else None
        conn = None
        if details.address:
            try:
                conn = await self._transport.dial(details.address)
            except (ConnectionError, OSError) as exc:
                if relay_via is None:
                    raise
                logger.info(f"direct dial {details.address} failed ({exc}); "
                            f"falling back to relay")
        if conn is None:
            assert relay_via is not None
            if not details.peer_key:
                raise ClientError("relay requires the provider's key")
            conn = await self.connect_relay(relay_via[0], relay_via[1],
                                            details.peer_key)
        peer = await Peer.connect(
            conn, self.identity, initiator=True, expected_remote_key=expected
        )
        return ProviderSession(peer, details, tracer=self.tracer)

    async def connect_relay(self, server_address: str, server_key: bytes,
                            provider_key_hex: str):
        """Open a server-spliced relay channel to a provider (the Noise
        handshake with the provider then runs THROUGH it — the server
        carries only ciphertext)."""
        from symmetry_tpu.network.relay import RelayedConnection, await_ready

        conn = await self._transport.dial(server_address)
        server_peer = await Peer.connect(
            conn, self.identity, initiator=True,
            expected_remote_key=server_key)
        try:
            await server_peer.send(MessageKey.RELAY_CONNECT,
                                   {"providerKey": provider_key_hex})
            # the relayId arrives in relayReady (shared wait helper —
            # one refusal-handling implementation for both roles)
            relay_id = await await_ready(server_peer)
        except ConnectionError as exc:
            await server_peer.close()
            raise ClientError(str(exc)) from exc
        except BaseException:
            # failed setup must not leak the dialed server connection —
            # failover retries would accumulate sockets
            await server_peer.close()
            raise
        return RelayedConnection(server_peer, relay_id)

    async def connect_direct(self, address: str, provider_key: bytes | None = None,
                             model_name: str = "") -> ProviderSession:
        """Direct connection to a known (possibly private) provider."""
        details = ProviderDetails(
            peer_key=provider_key.hex() if provider_key else "",
            address=address,
            model_name=model_name,
        )
        return await self.connect(details)

    async def discover(self, provider_key: bytes,
                       bootstrap: list[str]) -> ProviderDetails:
        """Decentralized discovery: resolve a provider by public key over
        the Kademlia DHT (network/dht.py) — no central server involved.
        Topic = discovery_key(provider_key), the reference's hyperswarm
        topic semantics. Raises ClientError when nobody has announced."""
        from symmetry_tpu.identity import discovery_key
        from symmetry_tpu.network.dht import DHTNode, parse_host_port

        try:
            boot = [parse_host_port(e) for e in bootstrap]
        except ValueError as exc:
            raise ClientError(str(exc)) from None
        node = DHTNode()
        await node.start("0.0.0.0", 0, bootstrap=boot)
        try:
            peers = await node.lookup(discovery_key(provider_key))
        finally:
            await node.stop()
        want = provider_key.hex()
        for peer in peers:
            if peer.get("publicKey") == want and peer.get("address"):
                return ProviderDetails(
                    peer_key=want,
                    address=peer["address"],
                    model_name=peer.get("modelName", ""),
                    raw=peer,
                )
        raise ClientError(
            f"provider {want[:12]}… not found on the DHT")
