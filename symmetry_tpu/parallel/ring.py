"""Ring attention: causal attention with K/V sharded over the `context`
mesh axis (long-context prefill, SURVEY §5.7 — net-new vs the reference,
which had no attention code at all).

Each device holds a sequence shard of Q/K/V. K/V shards rotate around the
ring via `jax.lax.ppermute` (XLA lowers neighbor permutes to ICI
send/recv), and every device folds each visiting K/V block into its local
queries with the same online-softmax (running max / running sum) merge the
flash kernel uses — so the full [S, S] score matrix never exists anywhere
and sequence length scales with the number of devices in the ring.

Causality note: with Q block-sharded, later ring steps are partially or
fully masked for low-index devices (they hold early queries). The rotation
still runs all n steps — static schedule, no data-dependent control flow —
matching how production ring/blockwise implementations behave under jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.attention import NEG_INF
from symmetry_tpu.utils.compat import shard_map


def _partial_attention(q, k, v, q_pos, kv_pos, seq_lens, m, l, acc):
    """Fold one K/V block into the running (m, l, acc) online softmax.

    Grouped GQA shapes throughout: q [B, Sq, H, D]; k/v [B, Sk, K, D];
    q_pos [B, Sq]; kv_pos [Sk]; seq_lens [B];
    m/l [B, K, G, Sq, 1]; acc [B, K, G, Sq, D] (H = K * G).
    """
    B, Sq, H, D = q.shape
    K, Sk = k.shape[2], k.shape[1]
    group = H // K
    scale = D ** -0.5

    qg = q.reshape(B, Sq, K, group, D)
    s = jnp.einsum("bskgd,btkd->bkgst", qg, k,
                   precision=jax.lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32) * scale  # [B,K,G,Sq,Sk]

    mask = (kv_pos[None, None, :] <= q_pos[:, :, None]) & (
        kv_pos[None, None, :] < seq_lens[:, None, None])        # [B,Sq,Sk]
    s = jnp.where(mask[:, None, None], s, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bkgst,btkd->bkgsd", p.astype(v.dtype), v,
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
    acc_new = acc * corr + pv
    return m_new, l_new, acc_new


def _ring_shard_fn(q, k, v, seq_lens, *, axis: str, shard_len: int,
                   n_shards: int):
    """Per-shard body under shard_map. q/k/v [B, Sc, H|K, D] local shards."""
    my = jax.lax.axis_index(axis)
    B, Sc, H, D = q.shape
    K = k.shape[2]
    group = H // K

    q_pos = my * shard_len + jnp.arange(Sc, dtype=jnp.int32)[None, :]
    q_pos = jnp.broadcast_to(q_pos, (B, Sc))

    m = jnp.full((B, K, group, Sc, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, K, group, Sc, 1), jnp.float32)
    acc = jnp.zeros((B, K, group, Sc, D), jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n_shards):
        src = (my - step) % n_shards  # whose K/V block we hold this step
        kv_pos = src * shard_len + jnp.arange(Sc, dtype=jnp.int32)
        m, l, acc = _partial_attention(q, k_cur, v_cur, q_pos, kv_pos,
                                       seq_lens, m, l, acc)
        if step < n_shards - 1:
            perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    l = jnp.maximum(l, 1e-30)  # fully-masked padded rows
    out = (acc / l).astype(q.dtype)                 # [B, K, G, Sc, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sc, H, D)


def ring_attention(
    q: jnp.ndarray,         # [B, S, H, D], S sharded over `axis`
    k: jnp.ndarray,         # [B, S, K, D]
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,  # [B] valid lengths (replicated)
    mesh,
    axis: str = "context",
) -> jnp.ndarray:
    """Causal ring attention over the context mesh axis. Returns [B,S,H,D]."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    B, S, H, D = q.shape
    if S % n:
        raise ValueError(f"sequence {S} not divisible by ring size {n}")
    shard_len = S // n

    fn = functools.partial(_ring_shard_fn, axis=axis, shard_len=shard_len,
                           n_shards=n)
    spec = P(None, axis, None, None)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
    )(q, k, v, seq_lens)
