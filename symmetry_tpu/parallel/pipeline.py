"""Inference pipeline parallelism: layer stages over the `stage` mesh axis.

SURVEY §2.3's PP row ("optional for serving; layer-stage sharding over DCN
for multi-host pods"): the model's stacked layers shard across pipeline
stages, activations flow stage-to-stage as point-to-point `ppermute`
transfers (no per-layer collectives — the property that makes PP the
DCN-friendly axis), and GPipe-style microbatching keeps every stage busy
once the pipe fills.

Schedule (M microbatches, P stages, static loop of M + P - 1 rounds):

    round t: stage s processes microbatch (t - s) when 0 <= t - s < M,
             then ppermutes its activation to stage s + 1.

Everything is SPMD under `shard_map`: inactive stages compute on garbage
and a `jnp.where` on the round index selects whether their cache/output
writes take effect — no data-dependent control flow, one compiled program.

Cache discipline: the KV cache shards its LAYER dim over `stage` (each
stage owns its layers' KV) and is viewed [L_local, M, Bm, ...] so a round
updates exactly the active microbatch's rows via dynamic slice in/out.
Layer indices inside a stage are local, which is what the local cache
shard expects (models/llama.py run_layers).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from symmetry_tpu.models.llama import KVCache, ModelConfig, run_layers
from symmetry_tpu.ops.norm import rms_norm
from symmetry_tpu.parallel.sharding import DEFAULT_RULES
from symmetry_tpu.utils.compat import shard_map

# Sharding rules for pipeline mode: layers (params AND cache) over `stage`.
PIPELINE_RULES = {**DEFAULT_RULES, "layers": "stage"}


def _mb_slice(arr, m, n_micro):
    """Static-shape microbatch slice along the batch dim (axis 0)."""
    bm = arr.shape[0] // n_micro
    return jax.lax.dynamic_slice_in_dim(arr, m * bm, bm, axis=0)


def _pp_shard_fn(params, tokens, cache: KVCache, seq_lens,
                 *, config: ModelConfig, n_stages: int, n_micro: int,
                 use_flash: bool):
    """Per-stage body. params['layers'] and cache.k/v arrive with the LOCAL
    layer shard (L/P leading dim); everything else replicated."""
    stage = jax.lax.axis_index("stage")
    B, S = tokens.shape
    bm = B // n_micro
    E = params["embed"].shape[1]

    positions = cache.lengths[:, None] + jnp.arange(S, dtype=jnp.int32)[None]
    kv_valid = cache.lengths + seq_lens

    # Local cache viewed per-microbatch: [L_loc, M, Bm, T, K, D].
    def split_mb(x, axis=1):
        return x.reshape(x.shape[:axis] + (n_micro, bm) + x.shape[axis + 1:])

    def merge_mb(x, axis=1):
        # inverse of split_mb: collapse the (M, Bm) pair back into B
        return x.reshape(x.shape[:axis] + (n_micro * bm,) + x.shape[axis + 2:])

    kc = split_mb(cache.k)
    vc = split_mb(cache.v)
    ksc = split_mb(cache.k_scale) if cache.quantized else None
    vsc = split_mb(cache.v_scale) if cache.quantized else None

    h_recv = jnp.zeros((bm, S, E), params["embed"].dtype)
    outputs = jnp.zeros((n_micro, bm, S, E), params["embed"].dtype)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def round_body(t, carry):
        h_recv, kc, vc, ksc, vsc, outputs = carry
        m = jnp.clip(t - stage, 0, n_micro - 1)  # my microbatch this round
        active = (stage <= t) & (t - stage < n_micro)

        # Stage 0 sources from the embedding; later stages from the wire.
        toks_m = _mb_slice(tokens, m, n_micro)
        h_in = jnp.where(stage == 0,
                         jnp.take(params["embed"], toks_m, axis=0), h_recv)

        mb_cache = KVCache(
            k=jax.lax.dynamic_index_in_dim(kc, m, 1, keepdims=False),
            v=jax.lax.dynamic_index_in_dim(vc, m, 1, keepdims=False),
            lengths=_mb_slice(cache.lengths, m, n_micro),
            k_scale=(jax.lax.dynamic_index_in_dim(ksc, m, 1, keepdims=False)
                     if ksc is not None else None),
            v_scale=(jax.lax.dynamic_index_in_dim(vsc, m, 1, keepdims=False)
                     if vsc is not None else None),
        )
        h_out, new_mb_cache = run_layers(
            params["layers"], h_in, mb_cache,
            _mb_slice(positions, m, n_micro), _mb_slice(kv_valid, m, n_micro),
            _mb_slice(seq_lens, m, n_micro), config, use_flash=use_flash,
            # Stage-sharded cache under shard_map: keep the XLA scatter
            # path (the fused append kernel is gated to unsharded caches).
            kv_append_ok=False)

        # Inactive rounds ran on garbage: select at MICROBATCH granularity
        # (old slice vs new slice) and do one in-place-able update — a
        # full-array where would stream the whole local cache through HBM
        # every round.
        def put(big, new_small, old_small):
            sel = jnp.where(active, new_small, old_small)
            return jax.lax.dynamic_update_index_in_dim(big, sel, m, 1)

        kc = put(kc, new_mb_cache.k, mb_cache.k)
        vc = put(vc, new_mb_cache.v, mb_cache.v)
        if ksc is not None:
            ksc = put(ksc, new_mb_cache.k_scale, mb_cache.k_scale)
            vsc = put(vsc, new_mb_cache.v_scale, mb_cache.v_scale)

        # The LAST stage's activations are the model output for microbatch m.
        done = active & (stage == n_stages - 1)
        outputs = jnp.where(
            done,
            jax.lax.dynamic_update_index_in_dim(outputs, h_out, m, 0),
            outputs)

        h_next = jax.lax.ppermute(h_out, "stage", perm)
        return h_next, kc, vc, ksc, vsc, outputs

    carry = (h_recv, kc, vc, ksc, vsc, outputs)
    for t in range(n_micro + n_stages - 1):  # static: P+M-1 rounds
        carry = round_body(t, carry)
    _, kc, vc, ksc, vsc, outputs = carry

    # Only the last stage wrote real outputs (zeros elsewhere): the psum
    # replicates them to every stage, satisfying the P() out_spec.
    outputs = jax.lax.psum(outputs, "stage")
    h = outputs.reshape(n_micro * bm, S, E)
    h = rms_norm(h, params["final_norm"], config.rms_eps)
    new_cache = KVCache(
        k=merge_mb(kc), v=merge_mb(vc), lengths=kv_valid,
        k_scale=merge_mb(ksc) if ksc is not None else None,
        v_scale=merge_mb(vsc) if vsc is not None else None,
    )
    return h, new_cache


def pipeline_forward_hidden(
    params: dict,
    config: ModelConfig,
    tokens: jnp.ndarray,      # [B, S] int32
    cache: KVCache,           # layer dim sharded over `stage`
    mesh,
    seq_lens: jnp.ndarray | None = None,
    *,
    n_microbatches: int = 2,
    prefill_flash: bool = False,
) -> tuple[jnp.ndarray, KVCache]:
    """Pipeline-parallel decoder trunk (embed → staged layers → final
    norm). Returns (hidden [B, S, E] on every stage, updated cache).

    Params/cache must be sharded with PIPELINE_RULES (layers → stage).
    The batch must divide n_microbatches; outputs are replicated across
    stages (only the last stage writes real outputs — the psum over
    `stage` at the end of the schedule replicates them everywhere).
    prefill_flash routes each stage's local attention through the Pallas
    flash kernel, under forward_hidden's empty-cache contract.
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape["stage"]
    B, S = tokens.shape
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} "
                         f"microbatches")
    if config.num_layers % n_stages:
        raise ValueError(f"{config.num_layers} layers not divisible by "
                         f"{n_stages} stages")
    other = [a for a in ("data", "context", "expert", "model")
             if mesh.shape[a] != 1]
    if other:
        # The in_specs below replicate non-layer dims; composing PP with
        # TP/DP/EP sharding needs those specs carried through — refuse
        # rather than silently all-gathering TP-sharded weights.
        raise ValueError(
            f"pipeline_forward_hidden shards only the stage axis; mesh has "
            f"non-trivial axes {other} — use a stage-only (sub)mesh")
    if seq_lens is None:
        seq_lens = jnp.full((B,), S, jnp.int32)
    # Same predicate as forward_hidden: the flash kernel handles sliding
    # windows natively (window-bounded block range).
    use_flash = prefill_flash and S > 1

    layer_spec = P("stage")
    param_specs = {
        "embed": P(), "final_norm": P(),
        "layers": jax.tree.map(lambda _: layer_spec, params["layers"]),
    }
    if "lm_head" in params:
        param_specs["lm_head"] = P()
    cache_specs = KVCache(
        k=layer_spec, v=layer_spec, lengths=P(),
        k_scale=layer_spec if cache.quantized else None,
        v_scale=layer_spec if cache.quantized else None,
    )

    fn = functools.partial(_pp_shard_fn, config=config, n_stages=n_stages,
                           n_micro=n_microbatches, use_flash=use_flash)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(param_specs, P(), cache_specs, P()),
        out_specs=(P(), cache_specs),
        # Pallas calls (flash prefill) inside the body don't carry VMA
        # annotations; output replication is by construction (the psum).
        check_vma=False,
    )(params, tokens, cache, seq_lens)
