"""Ulysses-style sequence parallelism: all-to-all head scatter.

The second long-context scheme of SURVEY §5.7 ("Ulysses-style head-scatter
as the alternative when head_count >= shard count"), complementing ring
attention (parallel/ring.py):

  ring:    K/V blocks rotate through every device (n ppermute steps);
           works for any head count, communication spread over the ring.
  ulysses: ONE all-to-all re-shards the data from sequence-sharded to
           head-sharded, every device runs plain full-sequence attention
           on its head subset, and a second all-to-all restores sequence
           sharding. Two collectives total, but requires
           num_kv_heads % shard_count == 0.

Correctness of the head split under GQA: heads are laid out k-major
(h = kv_head * group + g), so a contiguous split of the H axis into n
chunks is exactly a contiguous split of the KV-head axis — each device
gets (K/n) kv heads together with all their query heads, and the local
attention's h // group mapping is unchanged.

The local attention reuses ops/attention.py gqa_attention (absolute-
position causal masking, ragged seq_lens); on TPU the flash kernel could
drop in for the local step — the sharding transformation is the point of
this module and is attention-implementation-agnostic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.attention import gqa_attention
from symmetry_tpu.utils.compat import shard_map


def _ulysses_shard_fn(q, k, v, seq_lens, *, axis: str):
    """Per-shard body under shard_map.

    Local shapes in: q [B, Sc, H, D], k/v [B, Sc, K, D] (sequence-sharded).
    """
    B, Sc, H, D = q.shape

    def seq_to_heads(x):
        # [B, Sc, heads, D] -> [B, Sc * n, heads / n, D]: split the head
        # axis across devices, gather the full sequence in exchange.
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    q_full = seq_to_heads(q)   # [B, S, H/n, D]
    k_full = seq_to_heads(k)   # [B, S, K/n, D]
    v_full = seq_to_heads(v)

    S = q_full.shape[1]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    out = gqa_attention(q_full, k_full, v_full, positions, seq_lens)
    return heads_to_seq(out)   # [B, Sc, H, D]


def ulysses_attention(
    q: jnp.ndarray,         # [B, S, H, D], S sharded over `axis`
    k: jnp.ndarray,         # [B, S, K, D]
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,  # [B] valid lengths (replicated)
    mesh,
    axis: str = "context",
) -> jnp.ndarray:
    """Causal attention with sequence parallelism via head scatter.

    Returns [B, S, H, D], sequence-sharded like the inputs. Requires
    num_kv_heads (and so num_heads) divisible by the shard count and
    S divisible by it as well.
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    B, S, H, D = q.shape
    K = k.shape[2]
    if S % n:
        raise ValueError(f"sequence {S} not divisible by shard count {n}")
    if K % n or H % n:
        raise ValueError(
            f"ulysses needs heads divisible by shards: H={H}, K={K}, n={n} "
            f"(use ring attention otherwise)")

    fn = functools.partial(_ulysses_shard_fn, axis=axis)
    spec = P(None, axis, None, None)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
    )(q, k, v, seq_lens)
