"""Device mesh construction.

Axis convention (ordered outer→inner so the innermost axis maps to the
fastest interconnect — `model` collectives ride ICI, `data` may span DCN,
per the two-tier design in SURVEY §5.8):

    stage   — pipeline parallelism (parallel/pipeline.py): layer stages,
              point-to-point activation transfers only; DCN-safe
    data    — batch replication/sharding; DCN-safe (no per-layer collectives)
    context — sequence/ring-attention axis (long context, SURVEY §5.7)
    expert  — MoE expert parallelism (models/moe.py); ICI collectives
    model   — tensor parallelism; all-reduce per layer, must stay on ICI

A provider.yaml `tpu.mesh` mapping like {"data": 2, "model": 4} becomes a
MeshSpec; axes of size 1 are still materialized so PartitionSpecs can always
name them (XLA treats size-1 axes as free).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("stage", "data", "context", "expert", "model")


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape, e.g. MeshSpec(data=1, model=8)."""

    stage: int = 1
    data: int = 1
    context: int = 1
    expert: int = 1
    model: int = 1

    @classmethod
    def from_dict(cls, raw: dict[str, int]) -> "MeshSpec":
        unknown = set(raw) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXIS_ORDER}")
        return cls(**{k: int(v) for k, v in raw.items()})

    @property
    def size(self) -> int:
        size = 1
        for axis in AXIS_ORDER:
            size *= getattr(self, axis)
        return size

    def shape(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}


def build_mesh(spec: MeshSpec | dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from the spec over `devices` (default: all available).

    Device order follows jax.devices(), which on TPU enumerates in
    ICI-topology order — consecutive devices are ICI neighbours, so putting
    `model` innermost keeps its all-reduces on ICI.
    """
    if isinstance(spec, dict):
        spec = MeshSpec.from_dict(spec)
    if devices is None:
        devices = jax.devices()
    if spec.size > len(devices):
        raise ValueError(f"mesh needs {spec.size} devices, have {len(devices)}")
    grid = np.asarray(devices[: spec.size]).reshape(
        tuple(getattr(spec, a) for a in AXIS_ORDER)
    )
    return Mesh(grid, AXIS_ORDER)
