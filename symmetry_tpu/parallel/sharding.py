"""Logical-axis sharding: name every tensor dim, map names to mesh axes.

Models annotate parameters with logical axis names ("vocab", "embed",
"heads", ...); a rules table maps each name to a mesh axis (or None for
replicated). Changing the parallelism strategy = changing the rules, not
the model. The default rules implement megatron-style tensor parallelism:

    wq/wk/wv column-parallel (shard heads), wo row-parallel,
    w_gate/w_up column-parallel (shard mlp), w_down row-parallel,
    embedding + lm_head sharded over vocab.

Under jit with these NamedShardings, XLA inserts exactly the two
all-reduces per layer (after wo, after w_down) that hand-written megatron
TP would — but derived from shardings, not coded (SURVEY §5.8 tier (a)).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis name -> mesh axis name (None = replicated)
DEFAULT_RULES: dict[str, str | None] = {
    "batch": "data",
    "seq": None,
    "layers": None,        # stacked-layer leading dim (lax.scan over it)
    "embed": None,
    "heads": "model",
    "kv_heads": "model",
    "head_dim": None,
    "mlp": "model",
    "vocab": "model",
    "cache_seq": None,
    "context": "context",  # sequence-parallel activations (ring attention)
    "experts": "expert",   # MoE expert parallelism (models/moe.py)
}


def logical_to_spec(
    axes: tuple[str | None, ...], rules: dict[str, str | None] | None = None
) -> P:
    rules = DEFAULT_RULES if rules is None else rules
    mesh_axes = []
    for name in axes:
        if name is None:
            mesh_axes.append(None)
            continue
        if name not in rules:
            raise KeyError(f"no sharding rule for logical axis {name!r}")
        mesh_axes.append(rules[name])
    return P(*mesh_axes)


def shardings_for(
    logical_axes: Any,  # pytree of tuples of logical axis names
    mesh: Mesh,
    rules: dict[str, str | None] | None = None,
) -> Any:
    """Pytree of NamedShardings matching a pytree of logical-axes tuples."""
    from symmetry_tpu.ops.quant import QuantizedTensor

    # A logical-axes LEAF is a plain tuple of axis names. QuantizedTensor
    # is also a tuple (NamedTuple) but is a CONTAINER here — its q/scale
    # fields each hold their own axes tuple — so it must be recursed into,
    # not handed to logical_to_spec whole. PackedQuantizedTensor is a
    # registered pytree node (not a tuple), so tree.map recurses into it
    # on its own — models/llama.py packed_logical_axes builds axes trees
    # with packed containers and this map composes unchanged.
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_spec(axes, rules)),
        logical_axes,
        is_leaf=lambda x: (isinstance(x, tuple)
                           and not isinstance(x, QuantizedTensor)),
    )
