"""UDP stream transport: Python asyncio adapter over the C++ udpstream lib.

The reference's transport floor is udx-native — a C addon providing reliable
multiplexed UDP streams under every peer connection (SURVEY §2.2). This is
its equivalent here: native/udpstream/udpstream.cpp implements sequencing,
retransmission, flow-control windows, and frame boundaries; this module
binds it with ctypes and adapts the blocking C API onto asyncio via worker
threads. Addresses use the `udp://host:port` scheme; everything above the
Transport seam (Noise encryption, protocol, roles) is transport-agnostic
and runs unchanged over it.

The shared library auto-builds on first use when a toolchain is present
(`make -C native`); environments without one fall back to TCP.
"""

from __future__ import annotations

import asyncio
import ctypes
import os
import subprocess
from typing import Optional

from symmetry_tpu.transport.base import (
    Connection,
    ConnectionHandler,
    Listener,
    Transport,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build", "libudpstream.so")

_MAX_FRAME = 8 * 1024 * 1024


class UdpUnavailable(RuntimeError):
    pass


_lib: Optional[ctypes.CDLL] = None


def load_library() -> ctypes.CDLL:
    """Load (building if needed) the udpstream shared library."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        native_dir = os.path.join(_REPO_ROOT, "native")
        try:
            subprocess.run(["make", "-C", native_dir], check=True,
                           capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as exc:
            raise UdpUnavailable(
                f"libudpstream.so missing and build failed: {exc}") from exc
    lib = ctypes.CDLL(_LIB_PATH)
    lib.us_create.restype = ctypes.c_void_p
    lib.us_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.us_port.restype = ctypes.c_int
    lib.us_port.argtypes = [ctypes.c_void_p]
    lib.us_dial.restype = ctypes.c_uint64
    lib.us_dial.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                            ctypes.c_int]
    lib.us_accept.restype = ctypes.c_uint64
    lib.us_accept.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.us_send.restype = ctypes.c_int
    lib.us_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                            ctypes.c_char_p, ctypes.c_int]
    lib.us_recv.restype = ctypes.c_int
    lib.us_recv.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                            ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.us_close.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.us_destroy.argtypes = [ctypes.c_void_p]
    lib.us_send_raw.restype = ctypes.c_int
    lib.us_send_raw.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.us_recv_raw.restype = ctypes.c_int
    lib.us_recv_raw.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_int), ctypes.c_int]
    _lib = lib
    return lib


class RawChannel:
    """Connectionless datagrams over a udpstream ctx's socket (F_RAW).

    The NAT-punch side channel: packets leave from the SAME (addr, port)
    the stream protocol uses, so a raw datagram opens exactly the NAT
    mapping a later us_dial / inbound SYN will traverse."""

    def __init__(self, ctx: int) -> None:
        self._lib = load_library()
        self._ctx = ctx

    def send(self, host: str, port: int, payload: bytes) -> bool:
        return bool(self._lib.us_send_raw(
            self._ctx, host.encode(), port, payload, len(payload)))

    async def recv(self, timeout_s: float
                   ) -> tuple[bytes, str, int] | None:
        """One raw datagram as (payload, host, port), or None on timeout."""
        buf = ctypes.create_string_buffer(2048)
        ip = ctypes.create_string_buffer(16)
        port = ctypes.c_int(0)
        n = await asyncio.to_thread(
            self._lib.us_recv_raw, self._ctx, buf, len(buf), ip,
            ctypes.byref(port), int(timeout_s * 1000))
        if n < 0:
            return None
        return buf.raw[:n], ip.value.decode(), port.value


def _parse(address: str) -> tuple[str, int]:
    addr = address.removeprefix("udp://")
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"bad udp address {address!r}: expected udp://host:port")
    return host or "127.0.0.1", int(port)


class UdpConnection(Connection):
    def __init__(self, ctx: int, key: int, remote: str) -> None:
        self._lib = load_library()
        self._ctx = ctx
        self._key = key
        self._remote = remote
        self._closed = False
        self._buf = ctypes.create_string_buffer(_MAX_FRAME)

    async def send(self, frame: bytes) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        rc = await asyncio.to_thread(
            self._lib.us_send, self._ctx, self._key, frame, len(frame))
        if rc != 0:
            self._closed = True
            raise ConnectionError("udp stream closed")

    async def recv(self) -> bytes | None:
        while not self._closed:
            n = await asyncio.to_thread(
                self._lib.us_recv, self._ctx, self._key, self._buf,
                _MAX_FRAME, 500)
            if n > 0:
                return self._buf.raw[:n]
            if n == 0:
                continue  # timeout tick; re-check closed
            if n == -2:
                raise ConnectionError("frame exceeds maximum size")
            self._closed = True
            return None
        return None

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._lib.us_close(self._ctx, self._key)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def remote_address(self) -> str:
        return self._remote


class UdpListener(Listener):
    def __init__(self, ctx: int, host: str, handler: ConnectionHandler) -> None:
        self._lib = load_library()
        self._ctx = ctx
        self._host = host
        self._handler = handler
        self._closing = False
        self._task = asyncio.get_running_loop().create_task(self._accept_loop())

    @property
    def address(self) -> str:
        return f"udp://{self._host}:{self._lib.us_port(self._ctx)}"

    def raw_channel(self) -> RawChannel:
        """NAT-punch side channel on the LISTENER socket: raw datagrams
        from the same (addr, port) inbound streams arrive on, which is the
        port whose reflexive mapping the rendezvous must learn."""
        return RawChannel(self._ctx)

    async def _accept_loop(self) -> None:
        while not self._closing:
            key = await asyncio.to_thread(self._lib.us_accept, self._ctx, 500)
            if not key:
                continue
            conn = UdpConnection(self._ctx, key, "udp://?")
            task = asyncio.get_running_loop().create_task(self._handler(conn))
            task.add_done_callback(lambda t: t.exception())

    async def close(self) -> None:
        self._closing = True
        self._task.cancel()
        try:
            await self._task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        await asyncio.to_thread(self._lib.us_destroy, self._ctx)


class UdpTransport(Transport):
    """Transport over the native udpstream library (scheme `udp://`)."""

    scheme = "udp"

    def __init__(self) -> None:
        self._lib = load_library()
        self._dial_ctx: int | None = None

    async def listen(self, address: str, handler: ConnectionHandler) -> Listener:
        host, port = _parse(address)
        ctx = self._lib.us_create(host.encode(), port)
        if not ctx:
            raise OSError(f"cannot bind udp socket at {address}")
        return UdpListener(ctx, host, handler)

    def _ensure_dial_ctx(self) -> int:
        if self._dial_ctx is None:
            self._dial_ctx = self._lib.us_create(b"0.0.0.0", 0)
            if not self._dial_ctx:
                raise OSError("cannot create udp dial socket")
        return self._dial_ctx

    def dial_raw_channel(self) -> RawChannel:
        """Raw datagrams from the DIAL socket: a punch sent here opens the
        pinhole that this transport's subsequent dial() will traverse
        (same ctx, same port — network/natpunch.py)."""
        return RawChannel(self._ensure_dial_ctx())

    async def dial(self, address: str) -> Connection:
        host, port = _parse(address)
        ctx = self._ensure_dial_ctx()
        key = await asyncio.to_thread(
            self._lib.us_dial, ctx, host.encode(), port, 5000)
        if not key:
            raise ConnectionError(f"udp dial to {address} failed")
        return UdpConnection(ctx, key, address)
