"""In-memory transport: multi-node tests in one process, no sockets.

Implements the SURVEY §4 build implication — protocol/multi-node tests run as
multiple asyncio nodes over loopback pipes, the generalization of the
reference's mock-the-swarm test seam (__test__/cli.test.ts:4-13).
"""

from __future__ import annotations

import asyncio
from typing import Dict

from symmetry_tpu.transport.base import Connection, ConnectionHandler, Listener, Transport

_MAX_QUEUE = 256  # frames buffered per direction before send() backpressures

# The event loop keeps only weak refs to tasks; hold fire-and-forget tasks
# strongly or they can be garbage-collected mid-run.
_BACKGROUND_TASKS: set = set()


class MemoryConnection(Connection):
    def __init__(self, rx: asyncio.Queue, tx: asyncio.Queue, peer_name: str) -> None:
        self._rx = rx
        self._tx = tx
        self._peer_name = peer_name
        self._peer: "MemoryConnection | None" = None  # set by memory_pair
        self._closed = False
        self._eof = False
        # Frame-queue transport: boundaries ARE the unit, so nothing can
        # coalesce — writes == frames. Tracked anyway so emit-path stats
        # aggregate uniformly across transports.
        self._write_stats = {"writes": 0, "frames": 0,
                             "coalesced_frames": 0, "bytes": 0}

    async def send(self, frame: bytes) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        if self._peer is not None and self._peer._closed:
            # Mirror TCP: writing to a reset connection raises, it doesn't
            # buffer into the void until the queue wedges.
            raise ConnectionError("connection reset by peer")
        self._write_stats["writes"] += 1
        self._write_stats["frames"] += 1
        self._write_stats["bytes"] += len(frame)
        await self._tx.put(frame)  # Queue(maxsize) gives natural backpressure

    @property
    def write_stats(self) -> dict:
        return dict(self._write_stats)

    async def recv(self) -> bytes | None:
        if self._eof or self._closed:
            return None
        frame = await self._rx.get()
        if frame is None:
            self._eof = True
            return None
        return frame

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            # EOF for the local reader: a task blocked in recv() must wake up,
            # matching TcpConnection semantics (reader sees EOF after close).
            try:
                self._rx.put_nowait(None)
            except asyncio.QueueFull:
                pass  # queue has data → no reader is blocked; recv checks _closed

            try:
                self._tx.put_nowait(None)  # EOF marker for the peer
            except asyncio.QueueFull:
                # Peer is slow; spill the EOF without blocking close().
                task = asyncio.ensure_future(self._tx.put(None))
                _BACKGROUND_TASKS.add(task)
                task.add_done_callback(_BACKGROUND_TASKS.discard)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def remote_address(self) -> str:
        return self._peer_name


def memory_pair(a_name: str = "a", b_name: str = "b") -> tuple[MemoryConnection, MemoryConnection]:
    """A connected duplex pair — the unit-test workhorse."""
    q_ab: asyncio.Queue = asyncio.Queue(_MAX_QUEUE)
    q_ba: asyncio.Queue = asyncio.Queue(_MAX_QUEUE)
    a = MemoryConnection(rx=q_ba, tx=q_ab, peer_name=f"mem://{b_name}")
    b = MemoryConnection(rx=q_ab, tx=q_ba, peer_name=f"mem://{a_name}")
    a._peer, b._peer = b, a
    return a, b


class MemoryListener(Listener):
    def __init__(self, hub: "MemoryTransport", name: str) -> None:
        self._hub = hub
        self._name = name

    @property
    def address(self) -> str:
        return f"mem://{self._name}"

    async def close(self) -> None:
        self._hub._listeners.pop(self._name, None)


class MemoryTransport(Transport):
    """A process-local 'network': listeners keyed by name, dial by mem:// address."""

    scheme = "mem"

    def __init__(self) -> None:
        self._listeners: Dict[str, ConnectionHandler] = {}

    async def listen(self, address: str, handler: ConnectionHandler) -> Listener:
        name = address.removeprefix("mem://")
        if name in self._listeners:
            raise OSError(f"address in use: {address}")
        self._listeners[name] = handler
        return MemoryListener(self, name)

    async def dial(self, address: str) -> Connection:
        name = address.removeprefix("mem://")
        handler = self._listeners.get(name)
        if handler is None:
            raise ConnectionRefusedError(f"no listener at {address}")
        client_side, server_side = memory_pair(a_name="dialer", b_name=name)

        async def run_handler() -> None:
            try:
                await handler(server_side)
            except Exception as exc:
                from symmetry_tpu.utils.logging import logger

                logger.debug(f"peer {server_side.remote_address} dropped: {exc}")
            finally:
                await server_side.close()

        task = asyncio.ensure_future(run_handler())
        _BACKGROUND_TASKS.add(task)
        task.add_done_callback(_BACKGROUND_TASKS.discard)
        return client_side
