"""asyncio TCP transport with length-framed frames.

Production DCN/internet path. Where the reference rides UDX reliable-UDP
streams (dep udx-native; SURVEY §2.2), we use TCP via asyncio: same reliable
ordered byte-stream contract, with explicit 4-byte length framing restoring
message boundaries (symmetry_tpu.protocol.framing). Backpressure maps the
reference's `write()/drain` discipline (src/provider.ts:248-252) onto
`await writer.drain()`.
"""

from __future__ import annotations

import asyncio
from collections import deque

from symmetry_tpu.protocol.framing import FrameReader, encode_frame
from symmetry_tpu.transport.base import (
    Connection,
    ConnectionHandler,
    Listener,
    Transport,
    WriteCork,
)
from symmetry_tpu.utils.logging import logger


def _parse(address: str) -> tuple[str, int]:
    """Parse 'tcp://host:port', including IPv6 literals like tcp://[::1]:9410."""
    addr = address.removeprefix("tcp://")
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"bad tcp address {address!r}: expected tcp://host:port")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host or "127.0.0.1", int(port)


class TcpConnection(Connection):
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._reader = reader
        self._writer = writer
        self._frames = FrameReader()
        self._pending: deque[bytes] = deque()
        self._closed = False
        # Per-connection write cork: frames sent in the same event-loop
        # tick (the provider fan-out of one batched engine block to this
        # peer's streams) leave in one write+drain instead of one each.
        self._cork = WriteCork(self._write_drain)

    async def _write_drain(self, data: bytes) -> None:
        self._writer.write(data)
        await self._writer.drain()

    async def send(self, frame: bytes) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        await self._cork.send(encode_frame(frame))

    @property
    def write_stats(self) -> dict:
        return dict(self._cork.stats)

    async def recv(self) -> bytes | None:
        while not self._pending:
            try:
                chunk = await self._reader.read(65536)
            except (ConnectionResetError, BrokenPipeError):
                return None
            if not chunk:
                return None
            self._pending.extend(self._frames.feed(chunk))
        return self._pending.popleft()

    async def close(self) -> None:
        if not self._closed:
            self._closed = True  # set first: no new frames enter the cork
            try:
                # Settle the cork before closing the writer: a frame
                # send() accepted in this tick must reach the transport,
                # not be buffered-and-discarded by the teardown. Bounded:
                # a remote that stopped reading leaves the flusher wedged
                # in drain() forever — after the grace period, abort (the
                # writer.close() below breaks the stalled drain, whose
                # error path then fails any still-waiting senders).
                if self._cork.pending:
                    try:
                        await asyncio.wait_for(self._cork.settle(),
                                               timeout=5.0)
                    except asyncio.TimeoutError:
                        pass
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def remote_address(self) -> str:
        peer = self._writer.get_extra_info("peername")
        return f"tcp://{peer[0]}:{peer[1]}" if peer else "tcp://?"


class TcpListener(Listener):
    def __init__(self, server: asyncio.base_events.Server, address: str) -> None:
        self._server = server
        self._address = address

    @property
    def address(self) -> str:
        return self._address

    async def close(self) -> None:
        self._server.close()
        await self._server.wait_closed()


class TcpTransport(Transport):
    scheme = "tcp"

    async def listen(self, address: str, handler: ConnectionHandler) -> Listener:
        host, port = _parse(address)

        async def on_client(reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
            conn = TcpConnection(reader, writer)
            try:
                await handler(conn)
            except Exception as exc:
                # A misbehaving peer must cost one log line, not a traceback storm.
                logger.debug(f"peer {conn.remote_address} dropped: {exc}")
            finally:
                await conn.close()

        server = await asyncio.start_server(on_client, host, port)
        sock = server.sockets[0].getsockname()
        return TcpListener(server, f"tcp://{sock[0]}:{sock[1]}")

    async def dial(self, address: str) -> Connection:
        host, port = _parse(address)
        reader, writer = await asyncio.open_connection(host, port)
        return TcpConnection(reader, writer)
