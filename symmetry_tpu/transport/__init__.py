from symmetry_tpu.transport.base import Connection, Listener, Transport
from symmetry_tpu.transport.memory import MemoryTransport, memory_pair
from symmetry_tpu.transport.tcp import TcpTransport

__all__ = [
    "Connection",
    "Listener",
    "Transport",
    "MemoryTransport",
    "memory_pair",
    "TcpTransport",
]
