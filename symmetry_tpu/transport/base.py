"""Transport abstractions.

The reference's transport is the hyperswarm native stack (UDX reliable-UDP →
Noise secret-stream → DHT; SURVEY §1 layers A–C), reached only through
`swarm.join` + connection events. We make the transport an explicit, injectable
seam — the one good idea in the reference's test (it mocks hyperswarm whole,
__test__/cli.test.ts:4-13), generalized: protocol and node logic run unchanged
over in-memory pipes (tests), TCP (production), or a future C++/UDP transport.

A Connection carries opaque *frames* (bytes in, bytes out, boundaries
preserved); encryption layers above it (see symmetry_tpu.network.peer).
"""

from __future__ import annotations

import abc
import asyncio
from typing import AsyncIterator, Awaitable, Callable


class WriteCork:
    """Same-tick write coalescing (app-level cork) for stream transports.

    Frames sent while one event-loop tick is in progress — e.g. every
    per-request pump woken by one batched host frame writing to the same
    peer — append to a shared buffer; a single flusher writes the whole
    buffer in ONE transport write and ONE drain. Senders all await the
    shared flush future, so the existing per-send backpressure discipline
    (send returns only after drain) is preserved, and the buffer is
    written in send-call order, so per-stream ordering is too.

    The owner supplies `write_drain(data)` — the uncorked write+drain.
    Counters feed Connection.write_stats: `writes` is actual transport
    writes, `frames` frames accepted, `coalesced_frames` frames that
    piggybacked on an already-pending flush, `bytes` payload bytes.
    """

    def __init__(self, write_drain: Callable[[bytes], Awaitable[None]]
                 ) -> None:
        self._write_drain = write_drain
        self._buf = bytearray()
        self._fut: asyncio.Future | None = None
        self._task: asyncio.Task | None = None
        self.stats = {"writes": 0, "frames": 0, "coalesced_frames": 0,
                      "bytes": 0}

    async def send(self, data: bytes) -> None:
        self.stats["frames"] += 1
        self.stats["bytes"] += len(data)
        self._buf += data
        if self._fut is None:
            self._fut = asyncio.get_running_loop().create_future()
        else:
            self.stats["coalesced_frames"] += 1
        fut = self._fut
        # At most ONE flusher ever runs: its while-loop picks up batches
        # that accumulate during an in-flight drain, so frame bytes reach
        # write_drain strictly in send-call order no matter where
        # write_drain first suspends. (The task has no suspension point
        # between its last buffer check and returning, so a done() task
        # can never still pick our batch up.)
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._flush())
        # shield: the future is shared by every sender coalesced into
        # this batch — one cancelled sender must not cancel the future
        # out from under the others (their bytes are still written; a
        # cancelled future would fail healthy streams on a healthy
        # connection). Cancellation still propagates to THIS sender.
        await asyncio.shield(fut)

    @property
    def pending(self) -> bool:
        """True while accepted frames may not have hit the transport yet."""
        return self._task is not None and not self._task.done()

    async def settle(self) -> None:
        """Close barrier: wait until every accepted frame has been
        written (or failed its senders). The owner calls this before
        tearing the transport down — a frame send() accepted must not
        be silently discarded by a same-tick close racing the flusher."""
        while self._task is not None and not self._task.done():
            # wait() rather than await: the flusher's own failure mode is
            # to fail the sender futures, not to raise at the closer.
            await asyncio.wait([self._task])

    async def _flush(self) -> None:
        # Runs after the current tick's sends have buffered. Batches that
        # accumulate while a drain is in flight go out on the next loop
        # iteration — still one write each.
        while self._buf:
            buf = bytes(self._buf)
            self._buf.clear()
            fut, self._fut = self._fut, None
            try:
                self.stats["writes"] += 1
                await self._write_drain(buf)
            except BaseException as exc:  # noqa: BLE001 — fail all awaiters
                err = exc if isinstance(exc, Exception) else \
                    ConnectionError(f"write failed: {exc!r}")
                for f in (fut, self._fut):
                    if f is not None and not f.done():
                        f.set_exception(err)
                        f.exception()  # mark retrieved: awaiters may be gone
                self._fut = None
                self._buf.clear()
                if not isinstance(exc, Exception):
                    raise  # CancelledError & co: cleanup done, propagate
                return
            if fut is not None and not fut.done():
                fut.set_result(None)


class Connection(abc.ABC):
    """A reliable, ordered, frame-boundary-preserving duplex channel."""

    @abc.abstractmethod
    async def send(self, frame: bytes) -> None:
        """Send one frame. Applies backpressure (awaits drain) when buffers fill."""

    @property
    def write_stats(self) -> dict | None:
        """Emit-path write counters (see WriteCork.stats); None when the
        transport doesn't track them."""
        return None

    @abc.abstractmethod
    async def recv(self) -> bytes | None:
        """Receive one frame, or None on clean EOF."""

    @abc.abstractmethod
    async def close(self) -> None: ...

    @property
    @abc.abstractmethod
    def closed(self) -> bool: ...

    @property
    def remote_address(self) -> str:
        return "?"

    async def __aiter__(self) -> AsyncIterator[bytes]:
        while True:
            frame = await self.recv()
            if frame is None:
                return
            yield frame


ConnectionHandler = Callable[[Connection], Awaitable[None]]


class Listener(abc.ABC):
    """An accepting endpoint bound to an address."""

    @property
    @abc.abstractmethod
    def address(self) -> str:
        """Dialable address string, e.g. 'tcp://10.0.0.2:31337' or 'mem://a'."""

    @abc.abstractmethod
    async def close(self) -> None: ...


class Transport(abc.ABC):
    """Factory for listeners and outbound connections."""

    scheme: str = "?"

    @abc.abstractmethod
    async def listen(self, address: str, handler: ConnectionHandler) -> Listener: ...

    @abc.abstractmethod
    async def dial(self, address: str) -> Connection: ...
