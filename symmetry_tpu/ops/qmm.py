"""Quantized-weight Pallas matmuls: W8A8 (measured, not routed) and the
W8A16 fused-dequant kernel (`tpu.fused_dequant`, off by default).

The regime matters (all numbers measured on this v5e, fetch-fenced,
carry-dependent loops — tools/probe_s8_mxu.py, tools/bisect_decode.py):

  - DECODE (M ≈ slot count, ~128 rows): bandwidth-bound, and the floor is
    the int8→bf16 CONVERT, not HBM: XLA's mixed dot materializes a full
    bf16 copy of every int8 weight before each dot (~480 GB/s effective
    vs the 740-860 a pure bf16 matmul streams).
  - W8A8 (this file's first kernel): every int8 form is convert-
    throughput-limited; the s8×s8 kernel measured ~50% SLOWER than the
    XLA mixed dot in the full trunk (48.5 vs 32.1 ms). Decode stays on
    ops/quant.qmatmul's mixed dot.
  - PREFILL (M ≥ ~256 token rows): the s8×s8 MXU tiles measure
    ~172 TFLOP/s in ISOLATION at M=512, but routed into the real prefill
    path the end-to-end group time is identical (165.3 vs 167.6 ms) —
    prefill is not matmul-bound. Since W8A8 adds per-row activation-quant
    noise for zero measured gain, it is NOT routed.

W8A16 (`w8a16_matmul`, the round-8 convert-wall lever) is the one form
the rounds-3/4 study did NOT cover: weights stay int8 in HBM and are
dequantized TILE BY TILE in VMEM — the pallas_call grid pipeline
double-buffers each weight-tile DMA against the previous tile's MXU
work, so the convert rides inside the DMA/matmul pipeline instead of
materializing a full bf16 weight tensor per decode step. Activations
stay bf16 (no per-row activation-quant noise — exactly the path the
W8A8 negative result does not condemn). Weights are PRE-PACKED into the
kernel's [K/bk, N/bn, bk, bn] tile layout at load (ops/quant.py
pack_quantized) so each grid step's DMA is one contiguous read.
Numerics are the mixed dot's exactly: int8 values are exact in bf16,
products accumulate in f32, the per-output-channel scale is applied in
the epilogue — `(x @ q_bf16) * scale`, cast to the activation dtype.

The W8A8 kernel is kept as a correct, tested building block
(tests/test_qmm.py pins the arithmetic against a bit-exact integer
reference in interpret mode) and as the measurement record — a future
TPU generation or a genuinely matmul-bound workload may flip the
verdict. The activation is quantized dynamically per row to int8; the
s32 tile products are rescaled in the kernel epilogue by (row
activation scale × per-output-channel weight scale).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes measured on v5e (tools/probe_s8_mxu.py, M=512): smaller bn
# keeps more N-blocks for the grid, which generalizes better to narrow
# layers; (512, 1024) performs comparably at wide shapes.
BLOCK_N = 256
BLOCK_K = 512
MIN_ROWS = 32  # below this the MXU is mostly idle


def _kernel(x_ref, w_ref, xs_ref, ws_ref, o_ref, acc_scr, *, n_k: int,
            out_dtype):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        x_ref[:], w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == n_k - 1)
    def _():
        # epilogue: s32 -> f32, row scale × column scale, cast out
        o_ref[:] = (acc_scr[:].astype(jnp.float32)
                    * xs_ref[:] * ws_ref[:]).astype(out_dtype)


def _pick_block(dim: int, prefer: int) -> int | None:
    for cand in (prefer, 512, 256, 128, 64):
        if cand <= prefer and dim % cand == 0:
            return cand
    return None


def supports(m: int, k: int, n: int, backend: str) -> bool:
    """Static gate for the w8a8 kernel (shapes tileable, MXU-worthy M)."""
    return (backend == "tpu"
            and m >= MIN_ROWS
            and _pick_block(k, BLOCK_K) is not None
            and _pick_block(n, BLOCK_N) is not None)


def quantize_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8: x [M, K] -> (q [M, K] s8, scale [M, 1] f32)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def w8a8_matmul(
    x: jnp.ndarray,        # [M, K] float (bf16/f32)
    wq: jnp.ndarray,       # [K, N] int8
    w_scale: jnp.ndarray,  # [N] f32 per-output-channel
    *,
    out_dtype=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ dequant(wq) with the activation quantized per row to int8 and
    the product computed as native s8×s8 → s32 MXU tiles."""
    M, K = x.shape
    Kw, N = wq.shape
    assert K == Kw, (K, Kw)
    out_dtype = out_dtype or x.dtype
    bk = _pick_block(K, BLOCK_K)
    bn = _pick_block(N, BLOCK_N)
    if bk is None or bn is None:
        raise ValueError(f"untileable w8a8 shape K={K} N={N}")
    n_k = K // bk

    xq, xs = quantize_rows(x)
    ws = w_scale.astype(jnp.float32).reshape(1, N)

    return pl.pallas_call(
        functools.partial(_kernel, n_k=n_k, out_dtype=out_dtype),
        grid=(N // bn, n_k),
        in_specs=[
            pl.BlockSpec((M, bk), lambda n, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
            pl.BlockSpec((M, 1), lambda n, k: (0, 0)),
            pl.BlockSpec((1, bn), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((M, bn), jnp.int32)],
        interpret=interpret,
    )(xq, wq, xs, ws)


# ---------------------------------------------------------------------------
# W8A16 fused-dequant matmul (tpu.fused_dequant): bf16 activations against
# tile-packed int8 weights, dequantized in VMEM inside the DMA/matmul
# pipeline. See the module docstring for the regime analysis; the measured
# on-chip A/B lives in BASELINE.md and tools/probe_w8a16.py.

# Tile defaults: bn/bk are the DMA granularity AND the effective double-
# buffer depth lever (the pallas grid pipeline keeps the next (bk, bn)
# tile's DMA in flight behind the current tile's MXU work). 512×512 int8
# = 256 KiB per tile, two in flight, well inside VMEM next to the
# activation block and f32 accumulator. tools/probe_w8a16.py sweeps this.
W8A16_BLOCK_K = 512
W8A16_BLOCK_N = 512
# Row-block cap: x [bm, bk] + acc [bm, bn] f32 + out [bm, bn] must fit
# VMEM beside the weight tiles. Decode (M = slots ≈ 128) and verify
# (M = slots × (1+k)) fit in one block; wide prefill shapes grid over M.
W8A16_BLOCK_M = 1024
# On-TPU floors: int8 native tiling is (32, 128) — narrower tiles pad in
# VMEM and starve the DMA. Interpret mode (CPU tests) accepts any
# divisor down to 8 so the tiny presets exercise the real kernel.
_TPU_MIN_BK = 32
_TPU_MIN_BN = 128


def pick_w8a16_block(dim: int, prefer: int, floor: int = 8) -> int | None:
    """Largest candidate ≤ prefer (and ≥ floor) that divides dim."""
    for cand in (1024, 512, 256, 128, 64, 32, 16, 8):
        if floor <= cand <= prefer and dim % cand == 0:
            return cand
    return None


def w8a16_supports(k: int, n: int, backend: str) -> bool:
    """Static pack-time gate: True when (k, n) tiles into a layout the
    fused kernel can stream efficiently on `backend`. Untileable leaves
    stay in the flat [K, N] layout and keep the XLA mixed dot."""
    if backend == "tpu":
        bk = pick_w8a16_block(k, W8A16_BLOCK_K, floor=_TPU_MIN_BK)
        bn = pick_w8a16_block(n, W8A16_BLOCK_N, floor=_TPU_MIN_BN)
    else:
        bk = pick_w8a16_block(k, W8A16_BLOCK_K)
        bn = pick_w8a16_block(n, W8A16_BLOCK_N)
    return bk is not None and bn is not None


def _w8a16_kernel(x_ref, w_ref, ws_ref, o_ref, acc_scr, *, n_k: int,
                  out_dtype, apply_scale: bool = True):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[:]
    # The fused dequant: ONE (bk, bn) int8 tile, freshly DMA'd into VMEM
    # by the grid pipeline, converted to the activation dtype right here
    # — int8 values are exact in bf16, so this is the mixed dot's
    # arithmetic without its full-tensor bf16 materialization. The
    # per-output-channel scale waits for the epilogue (scaling commutes
    # with the K-sum).
    w = w_ref[0, 0].astype(x.dtype)
    acc_scr[:] += jax.lax.dot_general(
        x, w,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _():
        acc = acc_scr[:]
        if apply_scale:
            acc = acc * ws_ref[:]
        o_ref[:] = acc.astype(out_dtype)


@functools.partial(jax.jit,
                   static_argnames=("out_dtype", "apply_scale", "interpret"))
def w8a16_matmul(
    x: jnp.ndarray,        # [M, K] float (bf16/f32)
    w_tiles: jnp.ndarray,  # [K//bk, N//bn, bk, bn] int8 (pack_quantized)
    w_scale: jnp.ndarray,  # [N] f32 per-output-channel
    *,
    out_dtype=None,
    apply_scale: bool = True,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ dequant(w) with the weight streamed as pre-packed int8 tiles
    and dequantized in VMEM — semantically identical to ops/quant.qmatmul
    on the unpacked QuantizedTensor: (x @ q) accumulated f32, scaled per
    output channel, cast back to the activation dtype.

    apply_scale=False leaves the epilogue scale off (the f32 accumulator
    casts out raw) — the row-parallel sharded path sums the per-shard
    partials FIRST and scales after the reduce, matching the unfused
    GSPMD mixed dot's reduce-then-scale order exactly."""
    M, K = x.shape
    n_kt, n_nt, bk, bn = w_tiles.shape
    assert n_kt * bk == K, (w_tiles.shape, x.shape)
    N = n_nt * bn
    out_dtype = out_dtype or x.dtype
    bm = M if M <= W8A16_BLOCK_M else pick_w8a16_block(M, W8A16_BLOCK_M,
                                                       floor=64)
    if bm is None:
        raise ValueError(f"w8a16 row count {M} untileable past "
                         f"{W8A16_BLOCK_M}")
    ws = w_scale.astype(jnp.float32).reshape(1, N)

    return pl.pallas_call(
        functools.partial(_w8a16_kernel, n_k=n_kt, out_dtype=out_dtype,
                          apply_scale=apply_scale),
        grid=(M // bm, n_nt, n_kt),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            # One contiguous packed tile per grid step: this DMA is the
            # weight stream, and the grid pipeline double-buffers it.
            pl.BlockSpec((1, 1, bk, bn), lambda m, n, k: (k, n, 0, 0)),
            pl.BlockSpec((1, bn), lambda m, n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, w_tiles, ws)


def w8a16_apply(x: jnp.ndarray, w_tiles: jnp.ndarray,
                w_scale: jnp.ndarray, *, out_dtype=None,
                apply_scale: bool = True) -> jnp.ndarray:
    """qmatmul's fused-path entry: any leading batch shape on `x`,
    flattened to rows for the kernel. Falls back to the mixed dot on an
    unpacked view for row counts the kernel can't tile (never an engine
    shape — engine row counts are slot/bucket products)."""
    *lead, K = x.shape
    M = 1
    for d in lead:
        M *= d
    n_kt, n_nt, bk, bn = w_tiles.shape
    N = n_nt * bn
    out_dtype = out_dtype or x.dtype
    if M > W8A16_BLOCK_M and pick_w8a16_block(M, W8A16_BLOCK_M,
                                              floor=64) is None:
        # Mixed dot on an unpacked view, honouring the same out_dtype /
        # apply_scale contract as the kernel path.
        q = jnp.swapaxes(w_tiles, -3, -2).reshape(K, N)
        y = jax.lax.dot_general(
            x, q,
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if apply_scale:
            y = y * w_scale
        return y.astype(out_dtype)
    out = w8a16_matmul(x.reshape(M, K), w_tiles, w_scale,
                       out_dtype=out_dtype, apply_scale=apply_scale,
                       interpret=jax.default_backend() != "tpu")
    return out.reshape(*lead, N)


def w8a16_apply_sharded(x: jnp.ndarray, w) -> jnp.ndarray:
    """qmatmul's fused path for a mesh-sharded PackedQuantizedTensor
    (ops/quant.py — the leaf carries mesh + axis names as static aux):
    one shard_map whose body runs the SAME per-shard kernel on the local
    tiles. Column-parallel (n_axis set): every shard holds the full K
    and its N-slice — no collective, the output stays N-sharded, exactly
    where megatron TP wants wq/wk/wv/wg/wu/lm_head outputs. Row-parallel
    (k_axis set): each shard contracts its K-slice with the epilogue
    scale OFF, the f32 partials psum over the axis, and the per-output-
    channel scale applies after the reduce — the identical reduce-then-
    scale order the unfused GSPMD mixed dot lowers to, so fused and
    unfused mesh builds agree token for token.

    Specs are rebuilt from the leaf's static aux at trace time (ndim is
    all that varies — lax.scan strips the layers dim off the arrays but
    not the aux), which is what lets the same leaf serve every trunk
    program (prefill/chunk/decode/verify) with zero extra plumbing."""
    from jax.sharding import PartitionSpec as P

    from symmetry_tpu.utils.compat import shard_map

    mesh, k_ax, n_ax = w.mesh, w.k_axis, w.n_axis
    data = dict(zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)
    # Keep activations batch-sharded through the kernel when they are
    # (trace-time static shapes); otherwise run full rows per shard.
    bspec = ("data" if data > 1 and x.ndim >= 2 and x.shape[0] % data == 0
             else None)
    lead = (None,) * (x.ndim - 2)
    x_spec = P(bspec, *lead, k_ax)
    q_spec = P(*(None,) * (w.q.ndim - 4), k_ax, n_ax, None, None)
    s_spec = P(*(None,) * (w.scale.ndim - 1), n_ax)
    o_spec = P(bspec, *lead, n_ax)

    def body(xl, ql, sl):
        if k_ax is None:
            return w8a16_apply(xl, ql, sl)
        part = w8a16_apply(xl, ql, sl, out_dtype=jnp.float32,
                           apply_scale=False)
        y = jax.lax.psum(part, k_ax)
        return (y * sl).astype(x.dtype)

    return shard_map(body, mesh=mesh,
                     in_specs=(x_spec, q_spec, s_spec),
                     out_specs=o_spec, check_rep=False)(x, w.q, w.scale)
