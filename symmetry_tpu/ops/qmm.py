"""Native int8 MXU matmul (W8A8) — EXPERIMENTAL, not routed by default.

Engineering record of a measured dead end on v5e, kept because the
arithmetic is correct (tests/test_qmm.py) and other TPU generations may
change the verdict:

  - Every XLA int8 dot form — mixed bf16×s8, dequant-materialize, s8×s8
    with s32 accumulation — measures at the s8→float convert throughput
    (~270–480 GB/s effective), while bf16×bf16 streams at ~820 GB/s
    (tools/microbench_matmul.py, carry-dependent loop).
  - Hypothesis: feeding the MXU s8×s8 tiles directly from a Pallas kernel
    skips the convert. Microbenchmarks first showed ~590 GB/s, but that
    was a loop-invariant-hoisting artifact; with the input made
    carry-dependent the kernel measures ~258 GB/s (tools/probe_s8_mxu.py),
    and routed into the real decode trunk it is ~50% SLOWER end-to-end
    (48.5 vs 32.1 ms — tools/bisect_decode.py, BISECT_W8A8=1).
  - Conclusion: Mosaic's s8 dot path on v5e is no faster than XLA's, and
    the mixed dot in ops/quant.qmatmul stays the production path.

The activation is quantized dynamically per row (per token/slot) to int8;
the s32 tile products are rescaled in the kernel epilogue by
(row activation scale × per-output-channel weight scale).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Measured on v5e (tools/probe_s8_mxu.py): (bn=256, bk=512) and
# (512, 1024) both hit the ~590 GB/s mode; smaller bn keeps more N-blocks
# for the grid, which generalizes better to narrow layers.
BLOCK_N = 256
BLOCK_K = 512
MIN_ROWS = 32  # below this the MXU is mostly idle; mixed dot wins


def _kernel(x_ref, w_ref, xs_ref, ws_ref, o_ref, acc_scr, *, n_k: int,
            out_dtype):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        x_ref[:], w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(k == n_k - 1)
    def _():
        # epilogue: s32 -> f32, row scale × column scale, cast out
        o_ref[:] = (acc_scr[:].astype(jnp.float32)
                    * xs_ref[:] * ws_ref[:]).astype(out_dtype)


def _pick_block(dim: int, prefer: int) -> int | None:
    for cand in (prefer, 512, 256, 128, 64):
        if cand <= prefer and dim % cand == 0:
            return cand
    return None


def supports(m: int, k: int, n: int, backend: str) -> bool:
    """Static gate for the w8a8 kernel (shapes tileable, MXU-worthy M)."""
    return (backend == "tpu"
            and m >= MIN_ROWS
            and _pick_block(k, BLOCK_K) is not None
            and _pick_block(n, BLOCK_N) is not None)


def quantize_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8: x [M, K] -> (q [M, K] s8, scale [M, 1] f32)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def w8a8_matmul(
    x: jnp.ndarray,        # [M, K] float (bf16/f32)
    wq: jnp.ndarray,       # [K, N] int8
    w_scale: jnp.ndarray,  # [N] f32 per-output-channel
    *,
    out_dtype=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ dequant(wq) with the activation quantized per row to int8 and
    the product computed as native s8×s8 → s32 MXU tiles."""
    M, K = x.shape
    Kw, N = wq.shape
    assert K == Kw, (K, Kw)
    out_dtype = out_dtype or x.dtype
    bk = _pick_block(K, BLOCK_K)
    bn = _pick_block(N, BLOCK_N)
    if bk is None or bn is None:
        raise ValueError(f"untileable w8a8 shape K={K} N={N}")
    n_k = K // bk

    xq, xs = quantize_rows(x)
    ws = w_scale.astype(jnp.float32).reshape(1, N)

    return pl.pallas_call(
        functools.partial(_kernel, n_k=n_k, out_dtype=out_dtype),
        grid=(N // bn, n_k),
        in_specs=[
            pl.BlockSpec((M, bk), lambda n, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n)),
            pl.BlockSpec((M, 1), lambda n, k: (0, 0)),
            pl.BlockSpec((1, bn), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((M, bn), jnp.int32)],
        interpret=interpret,
    )(xq, wq, xs, ws)
