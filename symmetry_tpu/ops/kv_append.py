"""Fused decode-step KV append: quantize + in-place cache row write.

At decode (S=1) the XLA path for writing one token's K/V into the int8
cache costs ~14 kernels per layer — abs/max/div/round/cast chains, four
`kCustom` scatters, and (for the position-minor scale planes) a
full-plane select that streams ~5 MB per layer (round-4 HLO audit; the
quantize_kv ablation alone is ~4.4 ms of the 34.6 ms step at B=128,
tools/bisect_decode.py). This Pallas kernel replaces the whole cluster
with ONE call per layer: a B-slot grid where each program quantizes the
slot's new K/V row (identical math to ops/quant.quantize_kv: scale =
max(|x|, 1e-8)/127, q = clip(round(x/scale))) and writes payload + scale
in place through aliased output blocks addressed by scalar-prefetched
per-slot positions — no scatters, no full-plane traffic.

Out-of-range positions (a retired slot whose stale length reached
capacity) clamp to the last row, mirroring XLA scatter's drop-OOB
semantics closely enough: such rows are garbage either way and are
re-initialized by the next insert. Active slots never exceed capacity
(scheduler's finish guard).

Numerics: the kernel quantizes the bf16-ROUNDED activations (its operand
dtype), where the XLA fusion it replaces quantizes pre-rounding values
(rope's f32 intermediates survive into the fused quantize under
--xla_allow_excess_precision). Measured on-chip at layer 0: scales
within one bf16 ULP (0.36% rel), payloads within ±1 int8 step — inside
the int8-KV quantization noise floor by construction.

TPU-only (supports()); the XLA scatter path remains for CPU, prefill
(S>1), and sharded caches — a pallas_call has no GSPMD partitioning rule,
so under a kv_heads-sharded mesh XLA would gather the cache to one
device. Parity with the XLA path is pinned by tests/test_kv_append.py in
interpret mode.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Scale-plane block width along the position (lane) axis: the TPU
# lowering requires the minor block dim be a multiple of 128 (or the full
# dim), so the read-modify-write block is K x 128 f32 = 4 KB — still
# trivial next to the full-plane select it replaces. Capacities that
# aren't 128-multiples get a partial trailing block (masked write-back).
SCALE_BLOCK_T = 128


def _scale_block_t(capacity: int) -> int:
    return capacity if capacity < SCALE_BLOCK_T else SCALE_BLOCK_T


def supports(cache_capacity: int, head_dim: int, backend: str,
             sharded: bool) -> bool:
    # OPT-IN (SYMMETRY_KV_APPEND=1), not default — the full measured
    # verdict (BASELINE.md round 4):
    #   + bare trunk (one step per dispatch): 34.6 -> 31.6 ms
    #   o inside the block-decode scan (production): NEUTRAL — the small
    #     kernels' launch overhead pipelines behind compute there
    #   - HBM: with the kernel in the decode scan, the llama3-8b
    #     128-slot config OOMs deterministically in an isolated probe
    #     and intermittently mid-serving (staggered-arrival runs) —
    #     consistent with the aliased pallas call costing the while
    #     loop's buffer assignment a second cache-sized buffer. Zero
    #     in-scan win is not worth that; same precedent as ops/qmm.py
    #     (kernel kept, measured, not routed).
    if not os.environ.get("SYMMETRY_KV_APPEND"):
        return False
    if os.environ.get("SYMMETRY_NO_KV_APPEND"):
        return False
    return (backend == "tpu"
            and not sharded
            and head_dim % 128 == 0
            # A partial trailing scale block (capacity not 128-aligned)
            # sends Mosaic down a masked-writeback path measured 4 ms/step
            # SLOWER than the XLA scatter at the 128x672 point — while the
            # aligned 128x640 point wins 3 ms. (Unaligned capacities are a
            # bad idea for the XLA path too: 672 costs ~2 ms/step over 640
            # before any kernel enters the picture.)
            and (cache_capacity < SCALE_BLOCK_T
                 or cache_capacity % SCALE_BLOCK_T == 0))


def _kernel(pos_ref, layer_ref,            # scalar prefetch
            k_ref, v_ref,                  # [1, K, D] new row (post-rope)
            ck_in, cv_in, ks_in, vs_in,    # aliased cache blocks (in)
            ck_out, cv_out, ks_out, vs_out):
    b = pl.program_id(0)
    block_t = ks_in.shape[3]               # min(128, T)
    lane = pos_ref[b] % block_t
    # Mosaic cannot store a vector at a dynamic lane offset ("index in
    # dimension 3 is a multiple of 128" check) — poke the written lane
    # with a masked select over the whole (K, block_t) block instead.
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, block_t), 3)
    lane_mask = lane_iota == lane          # [1, 1, 1, block_t]
    for x_ref, q_out, s_in, s_out in ((k_ref, ck_out, ks_in, ks_out),
                                      (v_ref, cv_out, vs_in, vs_out)):
        x = x_ref[0].astype(jnp.float32)                   # [K, D]
        amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)  # [K, 1]
        scale = jnp.maximum(amax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        q_out[0, 0, 0] = q
        # Read-copy-modify: the scale block holds block_t positions'
        # scales; neighbours must survive the write-back.
        s_out[...] = jnp.where(lane_mask, scale[None, None, :, :], s_in[...])


@functools.partial(jax.jit, static_argnames=("interpret",))
def kv_append(
    cache_k: jnp.ndarray,    # [L, B, T, K, D] int8
    cache_v: jnp.ndarray,
    k_scale: jnp.ndarray,    # [L, B, K, T] f32 (position minor)
    v_scale: jnp.ndarray,
    k_new: jnp.ndarray,      # [B, K, D] post-rope K for this step
    v_new: jnp.ndarray,
    layer: jnp.ndarray,      # scalar int32
    positions: jnp.ndarray,  # [B] int32 write position per slot
    *,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    L, B, T, K, D = cache_k.shape
    pos = jnp.minimum(positions.astype(jnp.int32), T - 1)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape((1,))

    block_t = _scale_block_t(T)

    def payload_map(b, pos_ref, layer_ref):
        return (layer_ref[0], b, pos_ref[b], 0, 0)

    def scale_map(b, pos_ref, layer_ref):
        return (layer_ref[0], b, 0, pos_ref[b] // block_t)

    def new_map(b, pos_ref, layer_ref):
        return (b, 0, 0)

    payload_spec = pl.BlockSpec((1, 1, 1, K, D), payload_map)
    scale_spec = pl.BlockSpec((1, 1, K, block_t), scale_map)
    new_spec = pl.BlockSpec((1, K, D), new_map)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[new_spec, new_spec,
                  payload_spec, payload_spec, scale_spec, scale_spec],
        out_specs=[payload_spec, payload_spec, scale_spec, scale_spec],
    )
    out_k, out_v, out_ks, out_vs = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
            jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype),
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ],
        # Operand index space includes the 2 scalar-prefetch args: cache_k
        # is operand 4. In-place row writes, no copies of the ~GB caches.
        input_output_aliases={4: 0, 5: 1, 6: 2, 7: 3},
        interpret=interpret,
    )(pos, layer_arr, k_new, v_new, cache_k, cache_v, k_scale, v_scale)
    return out_k, out_v, out_ks, out_vs
