"""Int8 weight quantization (BASELINE config 5: llama3-70b int8 TP).

Symmetric per-output-channel int8: for w [.., in, out], each output column
gets scale = max|column| / 127, q = round(w / scale). The matmul computes
(x @ q) * scale — exact w.r.t. per-column scaling, and the int8 weight
halves HBM traffic vs bf16, which is the decode bottleneck (weights are
re-read every step).

QuantizedTensor is a pytree, so quantized params stack under lax.scan,
shard with NamedShardings, and donate exactly like dense ones.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    q: jnp.ndarray      # int8, same shape as the dense weight
    scale: jnp.ndarray  # f32, weight shape minus the contraction dim


class PackedQuantizedTensor(NamedTuple):
    """Tile-packed int8 weight for the fused W8A16 dequant matmul
    (ops/qmm.py w8a16_matmul, `tpu.fused_dequant`): the flat [.., K, N]
    int8 payload re-laid-out as [.., K/bk, N/bn, bk, bn] so each kernel
    grid step DMAs ONE contiguous tile from HBM. Same pytree discipline
    as QuantizedTensor — stacks under lax.scan (the leading layers dim
    strips off both leaves together) and donates like a dense leaf. The
    scale stays the flat per-output-channel [.., N]."""

    q: jnp.ndarray      # int8 [.., K/bk, N/bn, bk, bn] tile layout
    scale: jnp.ndarray  # f32 [.., N] per-output-channel


def quantize(w: jnp.ndarray, *, contract_axis: int = -2) -> QuantizedTensor:
    """Quantize a dense weight along its contraction (input) axis."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=jnp.squeeze(scale, axis=contract_axis))


def dequantize(qt: QuantizedTensor, dtype=jnp.float32,
               *, contract_axis: int = -2) -> jnp.ndarray:
    scale = jnp.expand_dims(qt.scale, contract_axis)
    return (qt.q.astype(jnp.float32) * scale).astype(dtype)


def qmatmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for dense arrays, QuantizedTensor, or PackedQuantizedTensor
    ([in, out] contraction).

    QuantizedTensor: a mixed-precision dot with the int8 operand passed
    directly — no `astype` on the weight, so XLA never materializes a
    bf16 copy as a SEPARATE op (for a 128k-vocab head that copy alone is
    >1 GB)... except it does anyway: on v5e the mixed dot's effective
    bandwidth (~480 GB/s) is the int8→bf16 convert's, not HBM's, because
    XLA converts the full weight ahead of the dot. Accumulates f32,
    applies the per-column scales, casts back to the activation dtype.

    PackedQuantizedTensor (`tpu.fused_dequant`): routes through the
    W8A16 Pallas kernel (ops/qmm.py w8a16_matmul) — int8 tiles stream
    from HBM double-buffered and dequantize in VMEM inside the
    DMA/matmul pipeline. Same arithmetic as the mixed dot (int8 exact in
    bf16, f32 accumulation, epilogue scale); the layout IS the routing,
    chosen once at weight load (engine/engine.py packs when the knob is
    on), so this hot-path dispatch stays a type check.

    Measured alternative, not routed: the native s8×s8 MXU kernel
    (ops/qmm.py) is ~50% slower in-trunk at decode-sized M and exactly
    NEUTRAL at prefill-sized M (165.3 vs 167.6 ms per coalesced prefill
    group on-chip, despite winning isolated matmul microbenchmarks —
    prefill is not matmul-bound). Since W8A8 would add activation-quant
    noise for zero measured gain, the mixed dot serves the default path.
    """
    if isinstance(w, PackedQuantizedTensor):
        from symmetry_tpu.ops.qmm import w8a16_apply

        return w8a16_apply(x, w.q, w.scale)
    if isinstance(w, QuantizedTensor):
        y = jax.lax.dot_general(
            x, w.q,
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * w.scale).astype(x.dtype)
    return x @ w


# One shared jitted quantizer: donating the dense original lets XLA reuse
# its buffer; both post-hoc tree quantization and quantized init go through
# this single definition.
quantize_jit = jax.jit(quantize, donate_argnums=(0,))


def quantize_tree(params: dict, keys: tuple[str, ...]) -> dict:
    """Quantize the named leaves of a params dict in place (donating the
    dense originals one at a time to bound peak memory)."""

    def visit(node):
        for name, child in list(node.items()):
            if isinstance(child, dict):
                visit(child)
            elif name in keys:
                node[name] = quantize_jit(child)

    visit(params)
    return params


# ---------------------------------------------------------------------------
# W8A16 tile packing (tpu.fused_dequant): performed ONCE at weight load so
# every decode-step weight DMA is contiguous. Packing is pure layout — the
# int8 payload bytes and the scales are untouched, so a packed tree is
# bit-equivalent to its flat original (unpack_quantized round-trips).


@functools.partial(jax.jit, static_argnames=("bk", "bn"))
def _pack_leaf(q: jnp.ndarray, bk: int, bn: int) -> jnp.ndarray:
    """[.., K, N] int8 → [.., K/bk, N/bn, bk, bn]. The tile transpose is
    a real copy; pack_tree replaces each leaf as it goes, so the flat
    original is freed right after and peak HBM overhead stays one int8
    leaf (~0.5 GB for an 8B lm_head), paid once at load."""
    *lead, K, N = q.shape
    q = q.reshape(*lead, K // bk, bk, N // bn, bn)
    return jnp.swapaxes(q, -3, -2)


def pack_quantized(qt: QuantizedTensor, *, bk: int | None = None,
                   bn: int | None = None):
    """Pack one QuantizedTensor into the fused kernel's tile layout, or
    return it unchanged when its shape doesn't tile on this backend (the
    leaf then keeps the XLA mixed dot — per-leaf fallback, no all-or-
    nothing). Explicit bk/bn override the kernel defaults (probe sweeps).
    """
    from symmetry_tpu.ops import qmm

    *_, K, N = qt.q.shape
    if bk is None and bn is None:
        if not qmm.w8a16_supports(K, N, jax.default_backend()):
            return qt
        floor_k = qmm._TPU_MIN_BK if jax.default_backend() == "tpu" else 8
        floor_n = qmm._TPU_MIN_BN if jax.default_backend() == "tpu" else 8
        bk = qmm.pick_w8a16_block(K, qmm.W8A16_BLOCK_K, floor=floor_k)
        bn = qmm.pick_w8a16_block(N, qmm.W8A16_BLOCK_N, floor=floor_n)
    elif bk is None or bn is None:
        raise ValueError("pack_quantized tile override needs BOTH bk and "
                         "bn (a partial override would mix a default-"
                         "derived block with the explicit one)")
    elif K % bk or N % bn:
        # Explicit overrides (probe sweeps) fail loudly, not deep inside
        # the jitted reshape — the default path's fallback-to-flat is for
        # load-time packing only.
        raise ValueError(f"tiles ({bk}, {bn}) do not divide weight "
                         f"({K}, {N})")
    return PackedQuantizedTensor(q=_pack_leaf(qt.q, bk, bn), scale=qt.scale)


def unpack_quantized(pt: PackedQuantizedTensor) -> QuantizedTensor:
    """Tile layout back to flat [.., K, N] (tests, re-export)."""
    *lead, n_kt, n_nt, bk, bn = pt.q.shape
    q = jnp.swapaxes(pt.q, -3, -2).reshape(*lead, n_kt * bk, n_nt * bn)
    return QuantizedTensor(q=q, scale=pt.scale)


def pack_tree(params: dict, keys: tuple[str, ...]) -> dict:
    """Pack the named QuantizedTensor leaves of a params dict in place
    (mirrors quantize_tree). Only 2-D weights and [L, K, N] layer stacks
    pack — MoE expert stacks ([L, E, K, N]) and untileable shapes keep
    the flat layout and the mixed dot."""

    def visit(node):
        for name, child in list(node.items()):
            if isinstance(child, dict):
                visit(child)
            elif (name in keys and isinstance(child, QuantizedTensor)
                  and child.q.ndim in (2, 3)):
                node[name] = pack_quantized(child)

    visit(params)
    return params


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token-per-head symmetric int8 for KV cache entries.

    x [..., D] -> (q int8 [..., D], scale f32 [...]): one scale per leading
    index (token × kv-head), amax over the head_dim axis. At decode the
    cache read is the second-largest HBM stream after the weights; int8
    halves it, and the scale array is D× smaller than the payload.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("shape", "scale", "dtype", "quantized"))
def make_leaf(key, shape: tuple[int, ...], scale: float, dtype,
              quantized: bool = False):
    """Random-init one parameter leaf fully inside ONE compiled program:
    normal → scale → cast (→ quantize). Nothing full-precision survives the
    program, so peak memory per leaf is its fused temporaries — which is
    what makes 8B-scale quantized init fit on one chip."""
    w = (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)
    return quantize(w) if quantized else w
