"""Int8 weight quantization (BASELINE config 5: llama3-70b int8 TP).

Symmetric per-output-channel int8: for w [.., in, out], each output column
gets scale = max|column| / 127, q = round(w / scale). The matmul computes
(x @ q) * scale — exact w.r.t. per-column scaling, and the int8 weight
halves HBM traffic vs bf16, which is the decode bottleneck (weights are
re-read every step).

QuantizedTensor is a pytree, so quantized params stack under lax.scan,
shard with NamedShardings, and donate exactly like dense ones.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    q: jnp.ndarray      # int8, same shape as the dense weight
    scale: jnp.ndarray  # f32, weight shape minus the contraction dim


@jax.tree_util.register_pytree_node_class
class PackedQuantizedTensor:
    """Tile-packed int8 weight for the fused W8A16 dequant matmul
    (ops/qmm.py w8a16_matmul, `tpu.fused_dequant`): the flat [.., K, N]
    int8 payload re-laid-out as [.., K/bk, N/bn, bk, bn] so each kernel
    grid step DMAs ONE contiguous tile from HBM. Same pytree discipline
    as QuantizedTensor — stacks under lax.scan (the leading layers dim
    strips off both leaves together) and donates like a dense leaf. The
    scale stays the flat per-output-channel [.., N].

    Mesh-aware: `k_axis`/`n_axis` name the MESH axes the weight's
    contraction/output dims are sharded over (None = replicated), and
    `mesh` is the Mesh itself. They ride the treedef as static aux data
    — lax.scan strips the stacked layers dim off the arrays while the
    axis names survive untouched, so qmatmul can rebuild per-rank
    PartitionSpecs from ndim at trace time and route the leaf through
    its shard_map'd per-shard kernel (ops/qmm.py w8a16_apply_sharded).
    A leaf packed without a mesh (or with both axes None) keeps the
    plain single-device dispatch."""

    __slots__ = ("q", "scale", "k_axis", "n_axis", "mesh")

    def __init__(self, q, scale, *, k_axis: str | None = None,
                 n_axis: str | None = None, mesh=None):
        self.q = q          # int8 [.., K/bk, N/bn, bk, bn] tile layout
        self.scale = scale  # f32 [.., N] per-output-channel
        self.k_axis = k_axis
        self.n_axis = n_axis
        self.mesh = mesh

    def tree_flatten(self):
        return (self.q, self.scale), (self.k_axis, self.n_axis, self.mesh)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, k_axis=aux[0], n_axis=aux[1], mesh=aux[2])

    def __repr__(self):
        return (f"PackedQuantizedTensor(q={self.q!r}, scale={self.scale!r}, "
                f"k_axis={self.k_axis!r}, n_axis={self.n_axis!r})")


def quantize(w: jnp.ndarray, *, contract_axis: int = -2) -> QuantizedTensor:
    """Quantize a dense weight along its contraction (input) axis."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=jnp.squeeze(scale, axis=contract_axis))


def dequantize(qt: QuantizedTensor, dtype=jnp.float32,
               *, contract_axis: int = -2) -> jnp.ndarray:
    scale = jnp.expand_dims(qt.scale, contract_axis)
    return (qt.q.astype(jnp.float32) * scale).astype(dtype)


def qmatmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for dense arrays, QuantizedTensor, or PackedQuantizedTensor
    ([in, out] contraction).

    QuantizedTensor: a mixed-precision dot with the int8 operand passed
    directly — no `astype` on the weight, so XLA never materializes a
    bf16 copy as a SEPARATE op (for a 128k-vocab head that copy alone is
    >1 GB)... except it does anyway: on v5e the mixed dot's effective
    bandwidth (~480 GB/s) is the int8→bf16 convert's, not HBM's, because
    XLA converts the full weight ahead of the dot. Accumulates f32,
    applies the per-column scales, casts back to the activation dtype.

    PackedQuantizedTensor (`tpu.fused_dequant`): routes through the
    W8A16 Pallas kernel (ops/qmm.py w8a16_matmul) — int8 tiles stream
    from HBM double-buffered and dequantize in VMEM inside the
    DMA/matmul pipeline. Same arithmetic as the mixed dot (int8 exact in
    bf16, f32 accumulation, epilogue scale); the layout IS the routing,
    chosen once at weight load (engine/engine.py packs when the knob is
    on), so this hot-path dispatch stays a type check.

    Measured alternative, not routed: the native s8×s8 MXU kernel
    (ops/qmm.py) is ~50% slower in-trunk at decode-sized M and exactly
    NEUTRAL at prefill-sized M (165.3 vs 167.6 ms per coalesced prefill
    group on-chip, despite winning isolated matmul microbenchmarks —
    prefill is not matmul-bound). Since W8A8 would add activation-quant
    noise for zero measured gain, the mixed dot serves the default path.
    """
    if isinstance(w, PackedQuantizedTensor):
        from symmetry_tpu.ops.qmm import w8a16_apply, w8a16_apply_sharded

        if w.mesh is not None and (w.k_axis or w.n_axis):
            return w8a16_apply_sharded(x, w)
        return w8a16_apply(x, w.q, w.scale)
    if isinstance(w, QuantizedTensor):
        y = jax.lax.dot_general(
            x, w.q,
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * w.scale).astype(x.dtype)
    return x @ w


# One shared jitted quantizer: donating the dense original lets XLA reuse
# its buffer; both post-hoc tree quantization and quantized init go through
# this single definition.
quantize_jit = jax.jit(quantize, donate_argnums=(0,))


def quantize_tree(params: dict, keys: tuple[str, ...]) -> dict:
    """Quantize the named leaves of a params dict in place (donating the
    dense originals one at a time to bound peak memory)."""

    def visit(node):
        for name, child in list(node.items()):
            if isinstance(child, dict):
                visit(child)
            elif name in keys:
                node[name] = quantize_jit(child)

    visit(params)
    return params


# ---------------------------------------------------------------------------
# W8A16 tile packing (tpu.fused_dequant): performed ONCE at weight load so
# every decode-step weight DMA is contiguous. Packing is pure layout — the
# int8 payload bytes and the scales are untouched, so a packed tree is
# bit-equivalent to its flat original (unpack_quantized round-trips).


def _pack_body(q: jnp.ndarray, bk: int, bn: int) -> jnp.ndarray:
    *lead, K, N = q.shape
    q = q.reshape(*lead, K // bk, bk, N // bn, bn)
    return jnp.swapaxes(q, -3, -2)


@functools.partial(jax.jit, static_argnames=("bk", "bn"))
def _pack_leaf(q: jnp.ndarray, bk: int, bn: int) -> jnp.ndarray:
    """[.., K, N] int8 → [.., K/bk, N/bn, bk, bn]. The tile transpose is
    a real copy; pack_tree replaces each leaf as it goes, so the flat
    original is freed right after and peak HBM overhead stays one int8
    leaf (~0.5 GB for an 8B lm_head), paid once at load."""
    return _pack_body(q, bk, bn)


def packed_q_spec(ndim: int, k_axis: str | None, n_axis: str | None):
    """PartitionSpec for a packed q of `ndim` dims ([.., K/bk, N/bn, bk,
    bn]): the K-grid dim carries the contraction shard, the N-grid dim
    the output shard, tile dims never shard. Because the per-shard tile
    counts divide (pack_quantized picks bk/bn against PER-SHARD K/N),
    slicing the global packed array along the grid dims IS the pack of
    the flat local shard — shard-wise bit-identical layouts."""
    from jax.sharding import PartitionSpec as P

    return P(*(None,) * (ndim - 4), k_axis, n_axis, None, None)


def packed_scale_spec(ndim: int, n_axis: str | None):
    """PartitionSpec for a packed scale [.., N]: with the output channels."""
    from jax.sharding import PartitionSpec as P

    return P(*(None,) * (ndim - 1), n_axis)


def _axis_size(mesh, axis: str | None) -> int:
    if mesh is None or axis is None:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)


def _pack_quantized_report(
    qt: QuantizedTensor, *, bk: int | None = None, bn: int | None = None,
    k_axis: str | None = None, n_axis: str | None = None, mesh=None,
) -> tuple:
    """pack_quantized plus the degrade reason: returns (leaf, reason)
    where reason is None when the leaf packed, else one of
    "untileable" (single-device shape the kernel can't tile),
    "shard_indivisible" (mesh axis doesn't divide K/N at all), or
    "shard_untileable" (per-shard K/N loses tileability)."""
    from symmetry_tpu.ops import qmm

    # A mesh axis of size 1 shards nothing — treat as replicated so the
    # leaf keeps the cheaper single-device dispatch.
    k_axis = k_axis if _axis_size(mesh, k_axis) > 1 else None
    n_axis = n_axis if _axis_size(mesh, n_axis) > 1 else None
    if k_axis is None and n_axis is None:
        mesh = None
    k_parts = _axis_size(mesh, k_axis)
    n_parts = _axis_size(mesh, n_axis)

    *_, K, N = qt.q.shape
    if K % k_parts or N % n_parts:
        return qt, "shard_indivisible"
    K_loc, N_loc = K // k_parts, N // n_parts
    if bk is None and bn is None:
        # Blocks are chosen against the PER-SHARD dims so the tile grid
        # [K/bk, N/bn] divides evenly across the mesh axes — that is
        # what makes the sharded packed layout equal the per-shard pack.
        floor_k = qmm._TPU_MIN_BK if jax.default_backend() == "tpu" else 8
        floor_n = qmm._TPU_MIN_BN if jax.default_backend() == "tpu" else 8
        bk = qmm.pick_w8a16_block(K_loc, qmm.W8A16_BLOCK_K, floor=floor_k)
        bn = qmm.pick_w8a16_block(N_loc, qmm.W8A16_BLOCK_N, floor=floor_n)
        if bk is None or bn is None:
            return qt, ("shard_untileable" if mesh is not None
                        else "untileable")
    elif bk is None or bn is None:
        raise ValueError("pack_quantized tile override needs BOTH bk and "
                         "bn (a partial override would mix a default-"
                         "derived block with the explicit one)")
    elif K_loc % bk or N_loc % bn:
        # Explicit overrides (probe sweeps) fail loudly, not deep inside
        # the jitted reshape — the default path's fallback-to-flat is for
        # load-time packing only.
        raise ValueError(f"tiles ({bk}, {bn}) do not divide weight "
                         f"({K}, {N}) per-shard ({K_loc}, {N_loc})")
    if mesh is None:
        tiles = _pack_leaf(qt.q, bk, bn)
    else:
        # Repack WITH the output placement declared, so the tile copy
        # lands shard-local instead of gathering and re-scattering.
        from jax.sharding import NamedSharding

        spec = packed_q_spec(qt.q.ndim + 2, k_axis, n_axis)
        tiles = jax.jit(
            functools.partial(_pack_body, bk=bk, bn=bn),
            out_shardings=NamedSharding(mesh, spec))(qt.q)
    return PackedQuantizedTensor(q=tiles, scale=qt.scale, k_axis=k_axis,
                                 n_axis=n_axis, mesh=mesh), None


def pack_quantized(qt: QuantizedTensor, *, bk: int | None = None,
                   bn: int | None = None, k_axis: str | None = None,
                   n_axis: str | None = None, mesh=None):
    """Pack one QuantizedTensor into the fused kernel's tile layout, or
    return it unchanged when its shape doesn't tile on this backend (the
    leaf then keeps the XLA mixed dot — per-leaf fallback, no all-or-
    nothing). Explicit bk/bn override the kernel defaults (probe sweeps).

    With `mesh` + `k_axis`/`n_axis` (mesh axis names for the contraction
    and output dims), the pack happens AFTER the sharding decision: tile
    blocks are picked against the per-shard K/N, the repack jit declares
    the packed NamedSharding, and the leaf carries the axis names so
    qmatmul routes it through the shard_map'd per-shard kernel."""
    leaf, _ = _pack_quantized_report(qt, bk=bk, bn=bn, k_axis=k_axis,
                                     n_axis=n_axis, mesh=mesh)
    return leaf


def unpack_quantized(pt: PackedQuantizedTensor) -> QuantizedTensor:
    """Tile layout back to flat [.., K, N] (tests, re-export)."""
    *lead, n_kt, n_nt, bk, bn = pt.q.shape
    q = jnp.swapaxes(pt.q, -3, -2).reshape(*lead, n_kt * bk, n_nt * bn)
    return QuantizedTensor(q=q, scale=pt.scale)


def pack_tree(params: dict, keys: tuple[str, ...], *,
              axes: dict | None = None, mesh=None,
              report: list | None = None) -> dict:
    """Pack the named QuantizedTensor leaves of a params dict in place
    (mirrors quantize_tree). Only 2-D weights and [L, K, N] layer stacks
    pack — MoE expert stacks ([L, E, K, N]) and untileable shapes keep
    the flat layout and the mixed dot.

    `axes` maps leaf name -> (k_mesh_axis, n_mesh_axis) for mesh-aware
    packing (models/llama.py pack_params resolves it from the logical-
    axis tree + sharding rules); `report`, when given, collects
    (path, reason) for every int8 leaf that stayed flat so the caller
    can log and count the degrades instead of silently eating them."""

    def note(path, reason):
        if report is not None:
            report.append((path, reason))

    def visit(node, prefix):
        for name, child in list(node.items()):
            if isinstance(child, dict):
                visit(child, prefix + (name,))
            elif name in keys and isinstance(child, QuantizedTensor):
                path = "/".join(prefix + (name,))
                if child.q.ndim not in (2, 3):
                    # MoE expert stacks [L, E, K, N]: the kernel has no
                    # expert grid dim; the mixed dot serves them.
                    note(path, "expert_stack")
                    continue
                k_ax, n_ax = (axes or {}).get(name, (None, None))
                leaf, reason = _pack_quantized_report(
                    child, k_axis=k_ax, n_axis=n_ax, mesh=mesh)
                node[name] = leaf
                if reason is not None:
                    note(path, reason)

    visit(params, ())
    return params


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token-per-head symmetric int8 for KV cache entries.

    x [..., D] -> (q int8 [..., D], scale f32 [...]): one scale per leading
    index (token × kv-head), amax over the head_dim axis. At decode the
    cache read is the second-largest HBM stream after the weights; int8
    halves it, and the scale array is D× smaller than the payload.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("shape", "scale", "dtype", "quantized"))
def make_leaf(key, shape: tuple[int, ...], scale: float, dtype,
              quantized: bool = False):
    """Random-init one parameter leaf fully inside ONE compiled program:
    normal → scale → cast (→ quantize). Nothing full-precision survives the
    program, so peak memory per leaf is its fused temporaries — which is
    what makes 8B-scale quantized init fit on one chip."""
    w = (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)
    return quantize(w) if quantized else w
