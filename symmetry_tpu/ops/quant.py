"""Int8 weight quantization (BASELINE config 5: llama3-70b int8 TP).

Symmetric per-output-channel int8: for w [.., in, out], each output column
gets scale = max|column| / 127, q = round(w / scale). The matmul computes
(x @ q) * scale — exact w.r.t. per-column scaling, and the int8 weight
halves HBM traffic vs bf16, which is the decode bottleneck (weights are
re-read every step).

QuantizedTensor is a pytree, so quantized params stack under lax.scan,
shard with NamedShardings, and donate exactly like dense ones.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    q: jnp.ndarray      # int8, same shape as the dense weight
    scale: jnp.ndarray  # f32, weight shape minus the contraction dim


def quantize(w: jnp.ndarray, *, contract_axis: int = -2) -> QuantizedTensor:
    """Quantize a dense weight along its contraction (input) axis."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=contract_axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=jnp.squeeze(scale, axis=contract_axis))


def dequantize(qt: QuantizedTensor, dtype=jnp.float32,
               *, contract_axis: int = -2) -> jnp.ndarray:
    scale = jnp.expand_dims(qt.scale, contract_axis)
    return (qt.q.astype(jnp.float32) * scale).astype(dtype)


def qmatmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for dense arrays or QuantizedTensor ([in, out] contraction).

    The int8→activation-dtype convert fuses into the dot's operand read on
    TPU, so HBM sees int8; scales apply to the [.., out] result columns.
    """
    if isinstance(w, QuantizedTensor):
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype)
    return x @ w


def quantize_tree(params: dict, keys: tuple[str, ...]) -> dict:
    """Quantize the named leaves of a params dict in place (donating the
    dense originals one at a time to bound peak memory)."""
    jq = jax.jit(quantize, donate_argnums=(0,))

    def visit(node):
        for name, child in list(node.items()):
            if isinstance(child, dict):
                visit(child)
            elif name in keys:
                node[name] = jq(child)

    visit(params)
    return params
