"""Token sampling: greedy / temperature / top-k / top-p, batched and jittable.

Controls are per-slot arrays, not Python scalars, so one compiled sampler
serves a continuous batch where every request carries its own temperature
(InferenceRequest sampling fields, provider/backends/base.py). temperature==0
selects greedy via masking rather than control flow — no recompiles, no
data-dependent branching under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.attention import NEG_INF


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] float
    key: jax.Array,             # PRNG key
    temperature: jnp.ndarray,   # [B] float; 0 => greedy
    top_p: jnp.ndarray,         # [B] float in (0, 1]; 1 => disabled
    top_k: jnp.ndarray,         # [B] int32; 0 => disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Scale by temperature (guard 0 to keep the math finite; result unused then).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    # Sort once, descending; apply top-k and top-p masks in sorted space.
    sorted_idx = jnp.argsort(scaled, axis=-1)[:, ::-1]
    sorted_logits = jnp.take_along_axis(scaled, sorted_idx, axis=-1)
    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]

    keep = jnp.ones((B, V), dtype=bool)
    # top-k: keep ranks < k (k==0 disables).
    k = jnp.where(top_k > 0, top_k, V)
    keep &= ranks < k[:, None]
    # top-p: keep the smallest prefix whose probability mass reaches p.
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept if the mass strictly before it is < p (always keeps rank 0)
    mass_before = cum - probs
    keep &= mass_before < top_p[:, None]

    masked = jnp.where(keep, sorted_logits, NEG_INF)
    choice_rank = jax.random.categorical(key, masked, axis=-1)  # [B]
    sampled = jnp.take_along_axis(sorted_idx, choice_rank[:, None], axis=-1)[:, 0]

    return jnp.where(temperature > 0, sampled.astype(jnp.int32), greedy)
