"""Token sampling: greedy / temperature / top-k / top-p, batched and jittable.

Controls are per-slot arrays, not Python scalars, so one compiled sampler
serves a continuous batch where every request carries its own temperature
(InferenceRequest sampling fields, provider/backends/base.py). temperature==0
selects greedy via masking rather than control flow — no recompiles, no
data-dependent branching under jit.

Perf note: a full [B, V] sort at V=128k costs more than the decode matmuls
for small models, so sampling is restricted to the top `cap` logits via
`lax.top_k` (top-k at small k is a cheap partial reduction on TPU). Greedy
and any top_k <= cap are exact; top-p loses only the probability mass beyond
the top `cap` tokens (< 1e-3 for typical LM distributions at cap=64).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from symmetry_tpu.ops.attention import NEG_INF

SAMPLING_TOP_CAP = 64


def _masked_top_logits(
    logits: jnp.ndarray,        # [..., V] float
    temperature: jnp.ndarray,   # [B] float; 0 => greedy
    top_p: jnp.ndarray,         # [B] float in (0, 1]; 1 => disabled
    top_k: jnp.ndarray,         # [B] int32; 0 => disabled
    cap: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The shared sampling-distribution core: temperature-scaled logits
    restricted to the top-`cap` window with the greedy/top-k/top-p keep
    mask applied (NEG_INF elsewhere). Returns (masked [..., cap], vocab
    indices [..., cap]). Factored out of sample_tokens so the speculative
    verify pass (verify_tokens) scores drafts against EXACTLY the
    distribution the decode path samples from — the acceptance rule is
    only unbiased if the two share one definition of the target."""
    extra = logits.ndim - 2  # broadcast per-slot controls over mid axes
    ctl = (slice(None),) + (None,) * extra

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[ctl + (None,)]

    # Partial sort: [..., cap] descending, with original vocab indices.
    top_logits, top_idx = jax.lax.top_k(scaled, cap)

    ranks = jnp.arange(cap, dtype=jnp.int32)
    # top-k: keep ranks < k (0 disables; anything beyond cap acts as cap).
    # Greedy (temperature == 0) is expressed as k = 1: with only rank 0
    # unmasked, a categorical draw deterministically returns the argmax —
    # one select lane, no separate greedy branch.
    k = jnp.where(top_k > 0, top_k, cap)
    k = jnp.where(temperature > 0, k, 1)
    keep = ranks < k[ctl + (None,)]
    # top-p: keep the smallest prefix whose probability mass reaches p.
    # (Mass is computed over the top-cap window — the tail beyond cap is
    # treated as zero, see module docstring.)
    probs = jax.nn.softmax(top_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept if the mass strictly before it is < p (always keeps rank 0)
    mass_before = cum - probs
    keep &= mass_before < top_p[ctl + (None,)]

    return jnp.where(keep, top_logits, NEG_INF), top_idx


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] float
    key: jax.Array,             # PRNG key — scalar, or [B] per-slot keys
    temperature: jnp.ndarray,   # [B] float; 0 => greedy
    top_p: jnp.ndarray,         # [B] float in (0, 1]; 1 => disabled
    top_k: jnp.ndarray,         # [B] int32; 0 => disabled
    cap: int = SAMPLING_TOP_CAP,
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    cap = min(cap, V)
    logits = logits.astype(jnp.float32)

    masked, top_idx = _masked_top_logits(logits, temperature, top_p, top_k,
                                         cap)
    if key.ndim:  # [B] per-slot keys: each row draws from its own stream
        choice_rank = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(key, masked)
    else:
        choice_rank = jax.random.categorical(key, masked, axis=-1)  # [B]
    sampled = jnp.take_along_axis(top_idx, choice_rank[:, None], axis=-1)[:, 0]
    return sampled.astype(jnp.int32)


def verify_tokens(
    logits: jnp.ndarray,        # [B, S, V] float; S = 1 + k draft lanes
    draft: jnp.ndarray,         # [B, k] int32 proposed tokens
    n_draft: jnp.ndarray,       # [B] int32 valid proposals per slot (0..k)
    key: jax.Array,             # [B] per-slot PRNG keys
    temperature: jnp.ndarray,   # [B] float; 0 => greedy
    top_p: jnp.ndarray,         # [B] float in (0, 1]
    top_k: jnp.ndarray,         # [B] int32
    cap: int = SAMPLING_TOP_CAP,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative-decoding acceptance (Leviathan et al.; PAPERS.md) over
    one batched verify forward. `logits[:, j]` is the target model's
    next-token distribution given the context plus draft[:, :j] — the
    verify pass fed [last_token, draft...] so position j scores proposal
    draft[:, j] and position n_draft holds the all-accepted bonus.

    Acceptance per slot: draft tokens are accepted left to right while
    u_j < p_target(draft_j) with u_j ~ U[0,1) — the n-gram drafter is a
    DETERMINISTIC proposer (q = point mass), for which this rule is the
    standard rejection test. On the first rejection the bonus token is
    drawn from the residual distribution (the target with the rejected
    proposal removed, renormalized); with every proposal accepted it is
    drawn from the target at the next position. Net effect: every emitted
    token is distributed EXACTLY as sequential sampling from the same
    masked distribution — greedy lanes (temperature 0 => a one-hot keep
    set) accept iff the draft equals the argmax, making speculative
    greedy output token-identical to plain decode.

    Returns (out [B, S], n_emit [B]): out[b, :n_emit[b]] are the tokens
    to emit this dispatch — n_emit-1 accepted drafts plus the bonus —
    and n_emit is always >= 1, so a slot with no proposals advances
    exactly like a plain decode step.
    """
    B, S, V = logits.shape
    cap = min(cap, V)
    logits = logits.astype(jnp.float32)

    masked, top_idx = _masked_top_logits(logits, temperature, top_p, top_k,
                                         cap)  # [B, S, cap] x2
    p = jax.nn.softmax(masked, axis=-1)  # target probs over the keep set

    # Probability the target assigns to each proposal (0 when the proposal
    # is outside the top-cap keep window). Lane S-1 has no proposal — pad
    # with zeros; the validity mask below keeps it out of the accept scan.
    draft_ext = jnp.concatenate(
        [draft, jnp.zeros((B, 1), draft.dtype)], axis=1)      # [B, S]
    match = top_idx == draft_ext[:, :, None]                  # [B, S, cap]
    p_draft = jnp.sum(jnp.where(match, p, 0.0), axis=-1)      # [B, S]

    ks = jax.vmap(lambda q: jax.random.split(q, 3))(key)      # [B, 3]
    u = jax.vmap(lambda q: jax.random.uniform(q, (S,)))(ks[:, 0])
    lane = jnp.arange(S, dtype=jnp.int32)[None, :]
    accept = (u < p_draft) & (lane < n_draft[:, None])        # [B, S]
    # Longest accepted prefix: rejections (and the padded tail) stop it.
    n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

    # Bonus-token candidates at every position, selected by n_acc below:
    #  - residual: the target with the rejected proposal removed (softmax
    #    over the remaining keep set renormalizes), for a mid-run stop;
    #  - full: a plain target draw, for the all-proposals-accepted lane.
    resid = jnp.where(match, NEG_INF, masked)
    r_rank = jax.vmap(lambda q, row: jax.random.categorical(q, row))(
        ks[:, 1], resid)                                      # [B, S]
    f_rank = jax.vmap(lambda q, row: jax.random.categorical(q, row))(
        ks[:, 2], masked)
    r_tok = jnp.take_along_axis(top_idx, r_rank[..., None], -1)[..., 0]
    f_tok = jnp.take_along_axis(top_idx, f_rank[..., None], -1)[..., 0]

    stop = n_acc[:, None]
    bonus_r = jnp.take_along_axis(r_tok, stop, axis=1)[:, 0]
    bonus_f = jnp.take_along_axis(f_tok, stop, axis=1)[:, 0]
    bonus = jnp.where(n_acc < n_draft, bonus_r, bonus_f)

    out = jnp.where(lane < stop, draft_ext, 0)
    out = jnp.where(lane == stop, bonus[:, None], out)
    return out.astype(jnp.int32), (n_acc + 1).astype(jnp.int32)
