from symmetry_tpu.provider.config import ConfigManager, TpuConfig

__all__ = ["ConfigManager", "TpuConfig", "SymmetryProvider"]


def __getattr__(name: str):
    # Lazy (PEP 562): SymmetryProvider pulls the identity/crypto stack,
    # which the engine-host and backend paths (engine/host.py, tpu_native)
    # never need — importing the package must not require `cryptography`.
    if name == "SymmetryProvider":
        from symmetry_tpu.provider.provider import SymmetryProvider

        return SymmetryProvider
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
