"""Provider configuration: YAML file, validated, typed access.

Same `provider.yaml` surface as the reference's ConfigManager
(reference: src/config.ts:5-51, schema src/types.ts:4-21) — fields
`apiHostname/apiPath/apiPort/apiProtocol/apiProvider/modelName/name/path/
public/serverKey/dataCollectionEnabled/maxConnections/apiKey` and `-c` CLI
override — extended with a `tpu` section for the native engine (mesh shape,
dtype, KV budget, checkpoint path) per the BASELINE.json north star.

Differences from the reference, on purpose:
  - `api*` fields are required only for HTTP-proxy backends; the flagship
    `tpu_native` backend needs none of them.
  - `apiKey` is never forwarded to the network (the reference sends the whole
    config, apiKey included, to the server at join — src/provider.ts:103-108).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any

import yaml

# Reference provider registry (src/constants.ts:22-29) + the TPU-native backends.
PROXY_PROVIDERS = ("litellm", "llamacpp", "lmstudio", "ollama", "oobabooga", "openwebui")
NATIVE_PROVIDERS = ("tpu_native", "echo")
API_PROVIDERS = PROXY_PROVIDERS + NATIVE_PROVIDERS

_REQUIRED_ALWAYS = ("apiProvider", "modelName", "name", "public", "serverKey")
# Reference's required list (src/config.ts:20-30) minus what tpu_native doesn't need.
_REQUIRED_PROXY = ("apiHostname", "apiPath", "apiPort", "apiProtocol")


class ConfigError(ValueError):
    pass


@dataclass
class TpuConfig:
    """Engine settings for the `tpu_native` backend."""

    mesh: dict[str, int] = field(default_factory=lambda: {"data": 1, "model": 1})
    dtype: str = "bfloat16"            # parameter/compute dtype
    quantization: str | None = None    # None | "int8" (weights)
    kv_quantization: str | None = None  # None | "int8" (KV cache)
    # W8A16 fused-dequant matmul (ops/qmm.py w8a16_matmul): int8 weights
    # pre-packed into the kernel's tile layout at load and dequantized in
    # VMEM inside the double-buffered DMA/matmul pipeline, instead of
    # XLA's full bf16 weight materialization per decode step (the
    # rounds-3/4 convert wall). Requires quantization: int8; composes
    # with tpu.mesh — tiles pack against the PER-SHARD dims after the
    # sharding decision, column-/row-parallel leaves run a shard_map'd
    # per-shard kernel, and a leaf whose shard loses tileability keeps
    # the mixed dot (counted in sym_qmm_fallback_total, never silent).
    # Off by default pending the on-chip A/B (BASELINE.md decode-floor
    # section; bench.py --fused-dequant / tools/probe_w8a16.py).
    fused_dequant: bool = False
    max_batch_size: int = 8            # decode slots (continuous batching)
    max_seq_len: int = 2048            # KV capacity per slot
    prefill_buckets: tuple[int, ...] = (128, 512, 2048)
    prefill_chunk: int | None = 256    # chunked-prefill step; None disables
    # Coalesced-prefill width cap per bucket: batch × bucket ≤ budget
    # (engine.prefill_batches_for). None → engine default (2048 tokens).
    prefill_token_budget: int | None = None
    # Shared-prefix KV cache HBM budget in MiB (engine/prefix_cache.py):
    # prompts sharing a system-prompt/few-shot preamble skip prefill for
    # the cached portion — the scheduler partitions admissions into
    # hit/miss dispatch units and the hit path copies the cached prefix
    # KV into the slot lane, prefilling only the uncached suffix. None/0
    # disables the cache entirely (no lookups, no extra warmup compiles).
    prefix_cache_mb: float | None = None
    # Tokens per KV block in the radix prefix cache's paged pool. Shared
    # prefixes match at THIS granularity (any whole-block prefix hits —
    # multi-turn histories of arbitrary length, not just bucket-aligned
    # preambles); smaller blocks share more but cost more index entries
    # and a longer re-prefilled tail on handoff. Must divide every
    # prefill bucket (enforced only when the cache is enabled).
    prefix_block_tokens: int = 16
    # Radix-cache summary gossip (pool routing): how many hot-path
    # block digests each engine's cache summary carries on its stats
    # probe — the PoolRouter's cache-affinity signal. 0 disables the
    # rider (members gossip nothing; placement is load-only). ~32 B of
    # wire per digest per heartbeat per member.
    prefix_gossip_blocks: int = 64
    # Minimum seconds between summary recomputes on the engine host —
    # per-member heartbeat probes inside this window share one cached
    # walk. Staleness decay in the router is governed by the POOL
    # heartbeat_s, not this knob.
    prefix_gossip_s: float = 2.0
    # Cache-affinity weight in pool placement: predicted-hit blocks
    # (from gossiped summaries, staleness-decayed) count this much
    # against load (queue slots) when scoring members — at 1.0 one
    # fresh predicted hit block outbids one queued request. 0 restores
    # pure least-loaded placement.
    pool_affinity_weight: float = 1.0
    # Prefill-role only: skip handoff-frame payloads for blocks this
    # host already shipped to the destination member (the receiver
    # adopts them by reference from its radix tree). The ledger is
    # per-destination and epoch-invalidated: pool routing stamps every
    # submit with the planned decode member and its ledger epoch
    # (bumped on member loss), so a respawned member's empty cache
    # drops its ledger instead of silently degrading every warm
    # handoff to a full re-prefill. Correctness never depends on it —
    # the receiver adopts the longest covered prefix either way.
    handoff_ledger: bool = True
    # Speculative decoding (engine/spec/): n-gram prompt-lookup drafting
    # with batched block verification. None/False disables it entirely —
    # the decode path and warmup compile set are then byte-identical to a
    # build without the feature. True enables defaults; an int sets
    # k_draft (draft tokens per slot per verify dispatch); a mapping may
    # set {k_draft, ngram_max, ngram_min, max_index_tokens}. Helps
    # workloads whose output
    # repeats spans of their own context (code edits, RAG quoting,
    # extractive answers); hurts incompressible chat — watch the
    # acceptance_rate counter in stats. Greedy output is token-identical
    # with the knob on or off; sampled lanes stay unbiased via rejection
    # sampling. Per-request opt-out: "speculative": false on the request.
    speculative: Any = None
    # Decode steps per device dispatch. 16 measured throughput-equal to
    # 64 at the llama3-8b/128-slot point (double-buffered dispatch hides
    # the round-trips) with ~2x lower TTFT and inter-chunk latency.
    decode_block: int = 16
    # Scheduler pipeline depth: decode blocks kept dispatched-but-unsynced
    # between loop iterations. At >= 2 the scheduler also moves every
    # non-dispatch per-block cost (detokenize, event encode, pipe emit,
    # bookkeeping) onto a bounded-queue emit worker, so the dispatch
    # thread's iteration approaches the bare dispatch cost (the
    # dispatch-gap fix, ROADMAP item 2). 1 = the pre-pipeline
    # double-buffer loop with inline emit, the A/B baseline. Token
    # streams are identical across depths (greedy and seeded); a deeper
    # pipeline only trades per-token wire latency (up to depth-1 extra
    # blocks of buffering) for steady throughput. Prefill-tier hosts in
    # disagg mode force 1 — they never decode.
    pipeline_depth: int = 2
    # Requests allowed to QUEUE beyond the decode slots before the
    # provider sheds new inference with a structured busy error (clients
    # fail over; the router steers by reported queue depth). None → one
    # full extra wave (= max_batch_size): an admitted request then waits
    # at most ~one slot rotation, bounding its TTFT near the per-request
    # service time instead of growing with the backlog. 0 disables
    # queueing (shed the moment every slot is busy).
    max_queue: int | None = None
    # symprof device-time attribution (utils/devprof.py): every Nth
    # engine dispatch of each kind (prefill/chunk/decode_block/verify/
    # adopt/seed_gather/scatter) is completion-probed — timestamped
    # block_until_ready — yielding per-kind DEVICE-duration histograms
    # and the dispatch-gap series (host idle between device blocks, the
    # rounds-3/4 steady-wire suspect) in stats/metrics/the Perfetto
    # device track. 0 (default) disables: one branch per dispatch,
    # CI-asserted like the metrics registry. Sampling serializes 1
    # dispatch in N, so keep N large enough that tok/s stays within 1%
    # (BASELINE.md Round 15 pre-registers the A/B).
    profile_sample: int = 0
    # Request-scoped tracing (utils/trace.py): bounded span/counter rings
    # in the scheduler and host, read through the host-pipe `trace` op and
    # exported as a Perfetto timeline (provider `trace` op, bench.py
    # --trace-out). Cheap enough to leave on (a few ring appends per
    # decode block); False empties the rings entirely — the bench A/B
    # knob for proving the overhead stays under 1%.
    tracing: bool = True
    # symledger per-request cost attribution (engine/ledger.py): the
    # scheduler apportions every dispatch's measured wall to the
    # requests it served (prefill/chunk exact, decode/verify blocks by
    # active-slot occupancy), each finish event carries a `costs` block
    # (device_s{phase}/queue_s/emit_s/wasted_s{reason}/saved_s), the
    # host STATS reply ships a bounded ring + aggregates, and the
    # provider folds per-request SLO attainment into windowed goodput
    # (sym_goodput_tokens_per_device_second) and feeds the autoscaler's
    # SLO-attaining numerator. False disables: one guarded branch per
    # dispatch (same overhead contract as metrics.enabled and
    # tpu.faults; BASELINE.md Round 20 pre-registers the ≤1% A/B).
    ledger: bool = True
    # TTFT-bounded admission: shed a new request when the provider's
    # ESTIMATED first-token wait (requests awaiting their first token ÷
    # recent first-token rate) exceeds this many seconds. Catches the
    # overload mode the in-flight bound can't: during a sustained-arrival
    # ramp the limiter is prefill dispatch rate, so the scheduler inbox
    # can hold seconds of wait while decode slots are still free. None
    # (default) disables the bound — a pure thundering-herd burst from
    # idle is admitted in full either way (no recent rate signal → no
    # shedding on ignorance).
    max_ttft_s: float | None = None
    # "process" (default, production): the engine runs in a host
    # subprocess behind a pipe — its GIL-held device syncs would
    # otherwise starve the provider's event loop and every stream's
    # latency with it (engine/host.py). "inproc": same-process engine
    # thread (tests, debugging).
    engine_isolation: str = "process"
    # Disaggregated prefill/decode (engine/disagg/). "unified" (default):
    # today's behavior, one engine does both phases. "disagg": the
    # backend runs a PREFILL host (admissions + chunked prefill only;
    # serializes each finished prompt's KV into a versioned handoff
    # frame) and a DECODE host (adopts frames through its prefix store —
    # auto-enabled with a default budget — and generates), with the
    # handoff broker routing submits to the prefill tier and piping
    # handoff → adopt between them; the pair is supervised as ONE unit
    # (either host dying triggers the restarting-shed + respawn path).
    # "prefill"/"decode" are the per-tier host roles the broker assigns —
    # set them directly only when driving engine/host.py by hand.
    # Requires engine_isolation "process" and a single-device engine.
    # Greedy output is token-identical disagg vs unified (test-enforced).
    role: str = "unified"
    # Per-tier overrides for role: disagg — {"prefill": {...}, "decode":
    # {...}}, each a mapping merged into that tier's tpu section; the
    # special key "faults" inside a tier lands as that HOST's top-level
    # faults mapping (chaos-test one tier of the pair).
    #
    # CROSS-MACHINE keys (engine/disagg/net.py — the handoff link):
    #   peer: "tcp://host:port"   decode/provider side: dial the prefill
    #                             node there instead of spawning a local
    #                             prefill host (NETWORK mode)
    #   listen: "tcp://0.0.0.0:port"  prefill-node side (node.py): bind
    #   inline: bool = false      backend self-hosts the PrefillNode
    #                             in-process and dials it at `peer` —
    #                             the full wire path in one provider
    #                             (bench --disagg-transport, CI smoke)
    #   chunk_kb: int = 1024      handoff chunk size on the link
    #   credit_mb: float = 64     receiver credit window (bounds
    #                             in-flight bytes; exhaustion throttles
    #                             prefill admissions via the sink)
    #   ack_timeout_s: float = 30 unacked transfer → retransmit
    #   max_retries: int = 2      then the request sheds retryable
    #   reconnect_base_s/reconnect_max_s   link redial backoff
    #   encrypt: bool = false     Noise handshake on the link (needs the
    #                             `cryptography` dependency); optional
    #   secret: str               identity seed name; peer_key: hex —
    #                             pin the expected remote static key
    disagg: dict[str, Any] | None = None
    # SLO-goodput autoscaler for the elastic disagg pool
    # (engine/disagg/autoscale.py): a controller tick inside the pool
    # heartbeat turns SLO burn rates + queue gauges + symprof's measured
    # per-tier device cost into real membership ops (spawn / drain /
    # rebalance the M×N shape). None (default) → the pool shape stays
    # whatever `disagg.pool` declared. Keys (all optional):
    #   enabled: bool = true          master switch
    #   max_members: int = 4          per-tier ceiling (floor is 1×1)
    #   dwell_s: float = 30.0         min seconds between decisions
    #   churn_cooldown_s: float = 60  scaling pause after a churn respawn
    #   spawn_burn: float = 1.0       fast-window SLO burn → spawn
    #   spawn_queue: float = 2.0      avg per-member load → spawn
    #   drain_load: float = 0.25      avg load at/under which a tier idles
    #   drain_ticks: int = 3          consecutive idle ticks → drain
    #   min_busy_s: float = 0.05      device-busy floor for the measured
    #                                 M:N rebalance signal
    autoscale: dict[str, Any] | None = None
    # Engine-host supervision (process isolation only): a heartbeat
    # watchdog piggybacked on the host stats op detects crashes AND
    # wedges with a much tighter deadline than the 15 s provider health
    # loop, fails every in-flight stream with a retryable
    # {"restarting": true} shed, and auto-respawns the host (warm
    # compile cache makes a config-identical respawn cheap) with
    # exponential backoff; only after max_respawns CONSECUTIVE failed
    # respawns does the circuit breaker open and the provider deregister
    # (the pre-supervisor behavior). Keys (all optional):
    #   enabled: bool = true         supervision on/off
    #   heartbeat_s: float = 5.0     watchdog probe cadence
    #   wedge_timeout_s: float = 5.0 no stats reply within this → wedged
    #   backoff_base_s: float = 0.5  first-respawn delay (doubles per
    #                                consecutive failure)
    #   backoff_max_s: float = 15.0  backoff ceiling
    #   max_respawns: int = 3        consecutive failures → circuit open
    #   min_stable_s: float = 5.0    a life must survive this long to
    #                                reset the failure count (crash-LOOPs
    #                                trip the breaker, not flap forever)
    #   spawn_timeout_s: float = 600 respawn must reach ready within this
    #   stop_grace_s: float = 30     shutdown drain before SIGKILL
    supervisor: dict[str, Any] | None = None
    pipeline_microbatches: int = 1     # GPipe microbatches (mesh stage > 1)
    checkpoint_path: str | None = None  # HF safetensors dir; None → random init
    # Cache the finished (stacked/transposed/quantized) param tree beside
    # the checkpoint on first load; restarts skip the whole conversion
    # (engine/weights.py save_warm_cache). SURVEY §5.4 warm restart.
    warm_cache: bool = True
    # Persistent XLA compilation cache (utils/compile_cache.py): True →
    # ~/.cache/symmetry_tpu/xla, a string → that directory, False → off.
    # A config-identical engine restart then compiles ~nothing.
    compile_cache: Any = True
    tokenizer_path: str | None = None   # tokenizer.json; None → byte tokenizer
    # Informational: every supported family (llama 3.x, mistral, qwen2,
    # mixtral-MoE, gemma) shares the decoder in models/llama.py, selected
    # by ModelConfig flags; checkpoints self-describe via config.json.
    model_family: str = "llama"
    model_preset: str | None = None     # e.g. "llama3-8b", "tiny" (tests)
    # Multi-host provider (SURVEY §7 stage 6): one logical provider backed
    # by N JAX processes. Keys: coordinator ("host:port"), num_processes,
    # process_id, dcn_data (hosts on the data axis). Rank 0 fronts the
    # network; other ranks run `python -m symmetry_tpu.provider --worker`.
    multihost: dict[str, Any] | None = None

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "TpuConfig":
        kwargs = {}
        for f in cls.__dataclass_fields__:
            if f in raw:
                kwargs[f] = tuple(raw[f]) if f == "prefill_buckets" else raw[f]
        unknown = set(raw) - set(cls.__dataclass_fields__)
        if unknown:
            raise ConfigError(f"unknown tpu config keys: {sorted(unknown)}")
        return cls(**kwargs)


class ConfigManager:
    """Reads + validates a provider.yaml (reference: src/config.ts:5-51)."""

    def __init__(self, config_path: str | None = None,
                 config: dict[str, Any] | None = None) -> None:
        if config is not None:
            self._config = dict(config)
        else:
            if config_path is None:
                config_path = default_config_path()
            with open(os.path.expanduser(config_path), "r", encoding="utf-8") as fh:
                loaded = yaml.safe_load(fh)
            if not isinstance(loaded, dict):
                raise ConfigError(f"config at {config_path} is not a mapping")
            self._config = loaded
        self._tpu = TpuConfig.from_dict(self._config.get("tpu") or {})
        self.validate()

    def validate(self) -> None:
        missing = [k for k in _REQUIRED_ALWAYS if self._config.get(k) is None]
        provider = self._config.get("apiProvider")
        if provider in PROXY_PROVIDERS:
            missing += [k for k in _REQUIRED_PROXY if self._config.get(k) is None]
        if missing:
            raise ConfigError(f"missing required config: {sorted(missing)}")
        if provider not in API_PROVIDERS:
            raise ConfigError(
                f"unknown apiProvider {provider!r}; expected one of {API_PROVIDERS}"
            )
        if not isinstance(self._config["public"], bool):
            # Reference enforces the same (src/config.ts:40-44).
            raise ConfigError("config field 'public' must be a boolean")
        if "maxConnections" in self._config and (
            not isinstance(self._config["maxConnections"], int)
            or self._config["maxConnections"] < 1
        ):
            raise ConfigError("maxConnections must be a positive integer")

    def get(self, key: str, default: Any = None) -> Any:
        return self._config.get(key, default)

    def get_all(self) -> dict[str, Any]:
        return dict(self._config)

    def public_view(self) -> dict[str, Any]:
        """Config as announced to server/clients — secrets stripped."""
        view = {k: v for k, v in self._config.items() if k not in ("apiKey", "tpu")}
        return view

    @property
    def tpu(self) -> TpuConfig:
        return self._tpu

    # Convenience typed accessors for the hot fields.
    @property
    def name(self) -> str:
        return self._config["name"]

    @property
    def model_name(self) -> str:
        return self._config["modelName"]

    @property
    def api_provider(self) -> str:
        return self._config["apiProvider"]

    @property
    def public(self) -> bool:
        return self._config["public"]

    @property
    def server_key(self) -> bytes:
        return bytes.fromhex(self._config["serverKey"])

    @property
    def max_connections(self) -> int:
        return self._config.get("maxConnections", 10)

    @property
    def data_collection_enabled(self) -> bool:
        return bool(self._config.get("dataCollectionEnabled", False))


def default_config_path() -> str:
    """~/.config/symmetry/provider.yaml (reference: src/symmetry.ts:13-17)."""
    return os.path.join(
        os.path.expanduser("~"), ".config", "symmetry", "provider.yaml"
    )


def write_default_config(path: str, *, name: str, server_key_hex: str,
                         model_name: str = "llama3:8b") -> None:
    """Scaffold a provider.yaml (reference: install.sh:35-50)."""
    cfg = {
        "name": name,
        "public": True,
        "serverKey": server_key_hex,
        "modelName": model_name,
        "apiProvider": "tpu_native",
        "maxConnections": 10,
        "dataCollectionEnabled": False,
        "path": os.path.dirname(os.path.expanduser(path)),
        "tpu": {"mesh": {"data": 1, "model": 1}, "dtype": "bfloat16"},
    }
    os.makedirs(os.path.dirname(os.path.expanduser(path)), exist_ok=True)
    with open(os.path.expanduser(path), "w", encoding="utf-8") as fh:
        yaml.safe_dump(cfg, fh, sort_keys=False)
