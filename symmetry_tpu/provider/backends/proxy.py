"""HTTP proxy backend: OpenAI-compatible external servers.

Capability parity with the reference's only inference path — POST
`{model, messages, stream:true}` to `{apiProtocol}://{apiHostname}:{apiPort}
{apiPath}` with optional Bearer apiKey (src/provider.ts:299-319), then parse
the streamed response per backend dialect (src/utils.ts:16-52):

  ollama / openwebui → OpenAI chunk `choices[0].delta.content`
  llamacpp           → `content`
  litellm / default  → `choices[0].delta.content` with literal-"undefined" guard

Chunks are forwarded raw (clients see the backend's native format, as in the
reference src/provider.ts:247) with the delta extracted once per chunk.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

import aiohttp

from symmetry_tpu.provider.backends.base import (
    BackendError,
    InferenceBackend,
    InferenceRequest,
    StreamChunk,
)
from symmetry_tpu.utils.json import safe_parse_json

_DATA_PREFIX = "data: "


def is_stream_with_data_prefix(line: str) -> bool:
    """SSE `data:` detection (reference: src/utils.ts:16-18)."""
    return line.startswith(_DATA_PREFIX)


def safe_parse_stream_response(line: str) -> Any | None:
    """Strip SSE prefix and parse (reference: src/utils.ts:20-31)."""
    if is_stream_with_data_prefix(line):
        line = line[len(_DATA_PREFIX):]
    if line.strip() in ("", "[DONE]"):
        return None
    return safe_parse_json(line)


def get_chat_data_from_provider(provider: str, chunk: Any) -> str:
    """Per-backend delta extraction (reference: src/utils.ts:33-52)."""
    if not isinstance(chunk, dict):
        return ""
    if provider == "llamacpp":
        content = chunk.get("content")
    else:
        choices = chunk.get("choices") or [{}]
        delta = choices[0].get("delta") if choices else None
        content = (delta or {}).get("content")
        if content is None:
            # Ollama-native shape: {"message": {"content": ...}}
            content = (chunk.get("message") or {}).get("content")
    if content is None or content == "undefined":  # literal guard, src/utils.ts:47
        return ""
    return str(content)


class ProxyBackend(InferenceBackend):
    def __init__(self, config: Any) -> None:
        self.name = config.api_provider
        self._url = (
            f"{config.get('apiProtocol')}://{config.get('apiHostname')}"
            f":{config.get('apiPort')}{config.get('apiPath')}"
        )
        self._model = config.model_name
        self._api_key = config.get("apiKey")
        self._session: aiohttp.ClientSession | None = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def healthy(self) -> bool:
        return True  # health = reachability; checked implicitly per request

    def _build_request(self, request: InferenceRequest) -> tuple[dict, dict]:
        """Reference: buildStreamRequest, src/provider.ts:299-319."""
        headers = {"Content-Type": "application/json"}
        if self._api_key:
            headers["Authorization"] = f"Bearer {self._api_key}"
        body: dict[str, Any] = {
            "model": self._model,
            "messages": request.messages,
            "stream": True,
        }
        if request.max_tokens is not None:
            body["max_tokens"] = request.max_tokens
        if request.temperature is not None:
            body["temperature"] = request.temperature
        if request.top_p is not None:
            body["top_p"] = request.top_p
        return body, headers

    async def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        if self._session is None:
            await self.start()
        body, headers = self._build_request(request)
        try:
            async with self._session.post(self._url, json=body, headers=headers) as resp:
                if resp.status != 200:
                    detail = (await resp.text())[:500]
                    raise BackendError(f"backend HTTP {resp.status}: {detail}")
                # Both SSE ("data: {...}\n\n") and JSON-lines backends split on newline.
                async for raw_line in resp.content:
                    line = raw_line.decode("utf-8", errors="replace").strip()
                    if not line:
                        continue
                    parsed = safe_parse_stream_response(line)
                    if parsed is None:
                        if line.endswith("[DONE]"):
                            yield StreamChunk(raw=line, text="", done=True)
                        continue
                    text = get_chat_data_from_provider(self.name, parsed)
                    done = bool(
                        isinstance(parsed, dict)
                        and (
                            parsed.get("done") is True  # ollama-native
                            or (parsed.get("choices") or [{}])[0].get("finish_reason")
                        )
                    )
                    yield StreamChunk(raw=line, text=text, done=done)
        except aiohttp.ClientError as exc:
            raise BackendError(f"backend connection failed: {exc}") from exc
