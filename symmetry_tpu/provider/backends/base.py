"""Inference backend seam.

The reference has exactly one backend shape — an external OpenAI-compatible
HTTP server it proxies to (src/provider.ts:299-319) — selected by the
`apiProvider` config out of a fixed registry (src/constants.ts:22-29). Here the
backend is a first-class interface so `tpu_native` (in-process JAX engine) and
the HTTP proxies are interchangeable:

    backend = get_backend(config)
    async for chunk in backend.stream(request): ...

Each StreamChunk carries both the raw wire form (forwarded verbatim to the
client, preserving the reference's passthrough semantics, src/provider.ts:247)
and the extracted text delta (for data collection — the reference re-parses
every chunk to get this, src/provider.ts:243-246; we extract once).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, AsyncIterator


@dataclass(slots=True)
class InferenceRequest:
    """An `inference` message payload (reference: src/types.ts:28-31)."""

    messages: list[dict[str, str]]
    key: str = "inference"
    # Sampling controls (tpu_native; proxies forward what their API accepts).
    max_tokens: int | None = None
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    seed: int | None = None
    # Speculative decoding override (tpu_native with tpu.speculative on):
    # False opts this request out of drafting; None defers to the engine.
    speculative: bool | None = None
    # Request trace context (utils/trace.py): the client-minted trace id
    # from the inference frame's "traceId" field; engine backends thread
    # it through the host pipe so scheduler spans correlate with the
    # client's on one Perfetto timeline. "" = untraced.
    trace_id: str = ""
    # End-to-end deadline in seconds from provider receipt (client
    # "deadline_s"). Engine backends thread it to the scheduler, which
    # sheds an already-expired request at admission instead of prefilling
    # work nobody is waiting for. None = no deadline.
    deadline_s: float | None = None
    # Stream resumption (client "resume" payload): `resume_text` is the
    # completion prefix the client already received from a provider that
    # died mid-stream — the backend continues generation from its end
    # (conditioning on prompt + resume_text, radix-cache-seeded on the
    # engine) and yields ONLY the continuation. `resume_tokens` is the
    # emitted-token count that text represents (positions a seeded
    # request's RNG lane); None lets the engine re-derive it from the
    # text. None resume_text = an ordinary request.
    resume_text: str | None = None
    resume_tokens: int | None = None


@dataclass(slots=True)
class StreamChunk:
    raw: str          # exact chunk forwarded to the client (SSE line / JSON line)
    text: str         # extracted completion delta ("" for control chunks)
    done: bool = False
    # Tokens this chunk represents. Engine backends report the true count
    # (a block-decode chunk carries many tokens, a finish's flush tail may
    # carry zero); proxy backends leave None and the provider falls back
    # to chunk counting — the reference's accounting (one chunk ≈ one
    # token, src/provider.ts:243-246). None and 0 differ on purpose:
    # 0 is an exact "no new tokens", None is "unknown, estimate".
    tokens: int | None = None
    # symledger cost block (engine/ledger.py), stamped on the done
    # chunk only: device_s{phase}/queue_s/emit_s/wasted_s{reason}/
    # saved_s as attributed by the scheduler (source "probed"/"blocked")
    # or estimated by a proxy backend (source "estimated"). None
    # mid-stream, and None everywhere while tpu.ledger is off.
    costs: dict | None = None


class ResumeJournal:
    """Per-request emitted-token journal: the backend's record of how
    many tokens each in-flight stream has relayed, so a crash/wedge/
    link-loss shed can stamp an ACCURATE `emitted` count into its
    structured error — the count a seeded resume uses to restore its
    RNG lane position. Tracked per stream via a handle (acquire on
    admission, release on every exit path — the lifecycle-checker
    contract: a leaked handle is a request the death path would stamp
    forever after it finished). The engine host's own journal (the
    stats-heartbeat rider) is merged in as a lower bound for streams
    whose frames died on the pipe.

    Single-event-loop discipline: every mutation happens on the
    provider's loop (stream tasks, reader tasks, death paths), so no
    lock is needed — same ownership argument as the backend queues."""

    def __init__(self) -> None:
        self._emitted: dict[str, int] = {}

    def track(self, request_id: str) -> "ResumeJournalHandle":
        """Open the journal entry for one stream; the returned handle
        must be released on every exit path."""
        self._emitted.setdefault(request_id, 0)
        return ResumeJournalHandle(self, request_id)

    def note(self, request_id: str, tokens: int) -> None:
        if tokens and request_id in self._emitted:
            self._emitted[request_id] += int(tokens)

    def merge(self, counts: dict | None) -> None:
        """Fold the engine host's heartbeat journal in (host-side counts
        of tokens WRITTEN to the pipe): for a tracked stream the larger
        count wins — frames the relay never saw still happened, and the
        shed must not understate what the engine emitted. (The resume
        itself always conditions on the CLIENT's text; this count is the
        shed's observability stamp and the wasted-work numerator.)"""
        if not isinstance(counts, dict):
            return
        for req_id, n in counts.items():
            key = str(req_id)
            if key in self._emitted and isinstance(n, int):
                self._emitted[key] = max(self._emitted[key], n)

    def get(self, request_id: str) -> int:
        return self._emitted.get(request_id, 0)

    def release(self, request_id: str) -> None:
        self._emitted.pop(request_id, None)


class ResumeJournalHandle:
    """One stream's journal entry. note() folds relayed tokens in;
    release() closes the entry (idempotent — the death path may have
    already stamped and the stream's finally still runs)."""

    __slots__ = ("_journal", "_request_id")

    def __init__(self, journal: ResumeJournal, request_id: str) -> None:
        self._journal = journal
        self._request_id = request_id

    def note(self, tokens: int) -> None:
        self._journal.note(self._request_id, tokens)

    def release(self) -> None:
        self._journal.release(self._request_id)


class InferenceBackend(abc.ABC):
    """A source of streamed completions."""

    name: str = "?"
    # Stream resumption support: True when stream() honors
    # InferenceRequest.resume_text (continues from its end, yields only
    # the continuation). The provider REFUSES resume requests against a
    # backend that would regenerate from scratch — the client would
    # splice a full completion onto its partial text — with a structured
    # error the client turns into a from-scratch restart.
    supports_resume: bool = False
    # Admission capacity. `slots` = requests served concurrently without
    # queueing (engine decode slots); `queue_limit` = total in-flight
    # (serving + queued) beyond which the provider sheds new inference
    # with a structured busy error instead of letting every queued client
    # wait unboundedly. None = unbounded — the reference's behavior
    # (nothing in /root/reference/src/provider.ts rejects on backlog, only
    # maxConnections caps peers), kept for the proxy/echo backends.
    slots: int | None = None
    queue_limit: int | None = None
    # TTFT-bounded admission (provider sheds when its estimated
    # first-token wait exceeds this); None = disabled.
    admission_ttft_bound_s: float | None = None

    @abc.abstractmethod
    def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        """Yield chunks for one completion. Raises BackendError on failure."""

    async def start(self) -> None:
        """Load weights / open pools. Called once before serving."""

    async def stop(self) -> None:
        """Release resources; called at provider shutdown."""

    async def healthy(self) -> bool:
        """Liveness for failure detection (SURVEY §5.3): engine wedge must
        unregister the provider."""
        return True

    async def trace_components(self) -> list[dict]:
        """Span-ring snapshots this backend contributes to the merged
        Perfetto export (utils/trace.export_perfetto component shape).
        Each entry's clock_offset_s must already be relative to THIS
        process's CLOCK_MONOTONIC (tpu_native applies its measured
        host-pipe offset before returning). Default: nothing to add."""
        return []


class BackendError(RuntimeError):
    pass


class BackendRestartingError(BackendError):
    """The engine host died (crash or wedge) and its supervisor is
    respawning it. RETRYABLE: the request itself is fine, the provider
    will be back — the provider relays this as a structured
    ``{"restarting": true}`` shed and clients fail over immediately
    (client.ProviderRestartingError joins the busy-shed backoff path)."""

    def __init__(self, message: str,
                 retry_after_s: float | None = None,
                 emitted: int | None = None) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s
        # Journal-stamped emitted-token count for the dying stream (None
        # when nothing streamed / unknown): the provider folds it into
        # the structured shed so the client's resume knows its RNG lane
        # position even when its own per-chunk counting is incomplete.
        self.emitted = emitted


class BackendDeadlineError(BackendError):
    """The request's end-to-end deadline expired before it was served
    (scheduler admission shed). NOT retryable — by definition nobody is
    waiting for the answer anymore."""


def get_backend(config: Any) -> InferenceBackend:
    """Instantiate the backend named by config.apiProvider."""
    provider = config.api_provider
    if provider == "echo":
        from symmetry_tpu.provider.backends.echo import EchoBackend

        return EchoBackend()
    if provider == "tpu_native":
        from symmetry_tpu.provider.backends.tpu_native import TpuNativeBackend

        return TpuNativeBackend(config)
    from symmetry_tpu.provider.backends.proxy import ProxyBackend

    return ProxyBackend(config)
