"""tpu_native backend: the in-process JAX engine as an apiProvider.

The flagship of the rebuild (BASELINE.json north star): where the reference
could only proxy to an external GPU server (reference: src/provider.ts:
210-214), this backend hosts the model itself — HF weights pjit-sharded over
the provider's TPU slice, continuous batching across peers, tokens streamed
back as OpenAI-style chat.completion.chunk SSE lines so existing clients
can't tell the difference (same wire format the proxy backends forward).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from typing import Any, AsyncIterator

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import AsyncSession, Scheduler
from symmetry_tpu.protocol.keys import HostOp, LinkOp
from symmetry_tpu.provider.backends.base import (
    BackendDeadlineError,
    BackendError,
    BackendRestartingError,
    InferenceBackend,
    InferenceRequest,
    ResumeJournal,
    StreamChunk,
)
from symmetry_tpu.utils.faults import FAULTS
from symmetry_tpu.utils.logging import logger as log

DEFAULT_MAX_NEW_TOKENS = 512


class _DecodeMember:
    """One decode-tier pool member: a local engine host with its own
    reader, probe waiters, clock offset, and supervision accounting —
    the per-member failure domain that replaces the pair's
    respawn-both-as-a-unit rule in pool mode."""

    __slots__ = ("id", "proc", "reader", "clock_offset", "waiters",
                 "down", "dead", "engine_alive", "spawned_at",
                 "respawn_failures", "circuit_open", "restarts",
                 "supervisor")

    def __init__(self, member_id: str) -> None:
        self.id = member_id
        self.proc: asyncio.subprocess.Process | None = None
        self.reader: asyncio.Task | None = None
        self.clock_offset = 0.0
        self.waiters: dict[str, list[asyncio.Future]] = {
            HostOp.STATS: [], HostOp.TRACE: [], HostOp.METRICS: [],
            HostOp.PROFILE: []}
        self.down = asyncio.Event()
        self.dead = False
        self.engine_alive = True
        self.spawned_at: float | None = None
        self.respawn_failures = 0
        self.circuit_open = False
        self.restarts = 0
        # This member's respawn-loop task: the autoscaler's retire path
        # must cancel exactly it (a supervisor left running would
        # respawn the member it just scaled away).
        self.supervisor: asyncio.Task | None = None

    @property
    def alive(self) -> bool:
        return (self.proc is not None and not self.dead
                and self.proc.returncode is None)


class TpuNativeBackend(InferenceBackend):
    """Two isolation modes (tpu.engine_isolation):

    "process" (default): the engine lives in a host subprocess behind a
    JSON-lines pipe (engine/host.py). Measured necessity, not taste: the
    in-process engine thread's GIL-held device syncs starved the
    provider's event loop so badly that every client's TTFT equalled the
    benchmark's wall time.

    "inproc": the engine thread shares this process (tests, debugging,
    and anything that needs direct engine access).

    Process mode is SUPERVISED (tpu.supervisor, on by default): a
    heartbeat watchdog piggybacked on the stats op detects host crashes
    and wedges with a tighter deadline than the 15 s provider health
    loop; detection fails every in-flight stream with a retryable
    BackendRestartingError (the structured {"restarting": true} shed
    clients fail over on) and auto-respawns the host — warm compile
    cache makes a config-identical respawn compile ~nothing — with
    exponential backoff. Only after max_respawns consecutive failed
    respawns does the circuit breaker open and healthy() go false, which
    is the pre-supervisor deregistration path.
    """

    name = "tpu_native"
    # Stream resumption: the host's resume admission continues generation
    # from the client's received text (radix-cache-seeded), so a resume
    # against this backend yields only the continuation.
    supports_resume = True

    def __init__(self, config: Any) -> None:
        self._config = config
        self._model_name = config.model_name
        self._engine: InferenceEngine | None = None
        self._scheduler: Scheduler | None = None
        self._command_loop = None
        self._proc: asyncio.subprocess.Process | None = None
        self._cfg_path: str | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        self._reader: asyncio.Task | None = None
        # --- disaggregated prefill/decode (tpu.role: disagg) ----------
        # The backend then runs a HOST PAIR: self._proc is the decode
        # host (primary: stats/trace/liveness target, serves the token
        # streams), self._prefill_proc the prefill host. Submits route
        # to the prefill tier; its `handoff` frames are forwarded to the
        # decode tier as `adopt` ops by the broker, which also carries
        # the request state across (engine/disagg/broker.py). The pair
        # is supervised as ONE unit — either process dying runs the
        # restarting-shed path and the respawn brings BOTH back.
        self._disagg = (getattr(config.tpu, "role", "unified")
                        or "unified") == "disagg"
        self._broker = None
        self._prefill_proc: asyncio.subprocess.Process | None = None
        self._prefill_reader: asyncio.Task | None = None
        self._prefill_cfg_path: str | None = None
        self._prefill_clock_offset: float = 0.0
        self._prefill_stats_waiters: list[asyncio.Future] = []
        self._prefill_trace_waiters: list[asyncio.Future] = []
        self._prefill_metrics_waiters: list[asyncio.Future] = []
        # --- cross-machine handoff link (tpu.disagg.peer) -------------
        # NETWORK mode: the prefill tier is NOT a local subprocess but a
        # PrefillNode (engine/disagg/node.py) reached over the handoff
        # link (engine/disagg/net.py) — this backend runs only the
        # decode host locally and dials the peer. `tpu.disagg.inline`
        # self-hosts the node in-process (full wire path, one process:
        # benches/smokes/tests). Link loss is a first-class failure:
        # in-flight migrations shed structured-retryable and the link
        # reconnects with backoff, independent of host supervision.
        self._link = None            # DecodeLink in network mode
        self._link_cfg = None
        self._inline_node = None     # in-process PrefillNode
        self._net_mode = False
        # --- elastic pool (tpu.disagg.pool) ---------------------------
        # POOL mode generalizes the pair into M prefill members × N
        # decode members (engine/disagg/pool.py): each prefill member is
        # a PrefillNode reached over its OWN DecodeLink (inline or
        # remote), each decode member a local engine host with its OWN
        # supervision domain. The PoolRouter places each request on the
        # least-loaded healthy prefill member and routes its KV handoff
        # to a decode member by queue-depth gauges; node death, link
        # loss, and deliberate drain are membership churn — in-flight
        # migrations on a lost member are RE-PLACED on a survivor (the
        # structured-retryable shed only fires when no survivor exists).
        self._pool_mode = False
        self._pool_cfg = None
        self._pool = None                  # PoolRouter
        self._plinks: dict[str, Any] = {}  # prefill member id -> DecodeLink
        self._inline_nodes: list[Any] = []
        self._pool_submits: dict[str, dict] = {}  # full submit ops for
                                                  # re-placement
        self._decode_members: dict[str, _DecodeMember] = {}
        self._pool_tasks: list[asyncio.Task] = []
        self._replace_tasks: set[asyncio.Task] = set()
        # --- SLO-goodput autoscaler (tpu.autoscale, pool mode only) ---
        # A PoolAutoscaler ticks inside the pool heartbeat and its
        # decisions become real member lifecycle events through the
        # member factory below: spawn = a fresh _DecodeMember /
        # inline PrefillNode, drain = drain-before-kill + retire.
        self._autoscaler = None
        self._member_seq: dict[str, int] = {}   # next member index/tier
        self._node_by_member: dict[str, Any] = {}  # prefill id -> node
        self._retiring: set[str] = set()  # fence: leave/down callbacks
                                          # of a deliberate retire are
                                          # not churn
        self._scale_task: asyncio.Task | None = None
        self._prev_busy: dict[str, float] = {}  # member -> device_s_total
        # Gates the pool's supervision/heartbeat tasks: set before the
        # first member spawns (they must not bail while start() is
        # still assembling the pool) and cleared first thing in stop().
        self._pool_active = False
        # Cache-affine routing signal: a provider-side ROUTING tokenizer
        # (same tokenizer files as the hosts', so it produces identical
        # prompt ids → identical causal block digests to the gossiped
        # cache summaries). Lazily built on the first pool placement;
        # False = construction failed once — permanent load-only
        # fallback, logged once, never retried per request.
        self._route_tok: Any = None
        # The provider's SLO burn-rate monitor (attached after
        # construction): the pool heartbeat reads its live fast-window
        # burn and feeds PoolRouter.update_gauges — the placement
        # tie-break input that was plumbed but never fed live.
        self._slo_monitor = None
        if self._disagg:
            from symmetry_tpu.engine.disagg import (
                HandoffBroker, LinkConfig, PoolConfig)

            self._broker = HandoffBroker()
            self._broker.tracer.enabled = bool(
                getattr(config.tpu, "tracing", True))
            self._link_cfg = LinkConfig(
                getattr(config.tpu, "disagg", None))
            self._net_mode = self._link_cfg.network_mode
            self._pool_cfg = PoolConfig(
                getattr(config.tpu, "disagg", None))
            self._pool_mode = self._pool_cfg.enabled
        self._started = False
        self._host_dead = False
        self._engine_alive = True  # host-reported scheduler liveness
        self._stats_waiters: list[asyncio.Future] = []
        self._trace_waiters: list[asyncio.Future] = []
        self._metrics_waiters: list[asyncio.Future] = []
        self._profile_waiters: list[asyncio.Future] = []
        # --- engine-host supervision (process mode) -------------------
        sup = config.tpu.supervisor or {}
        self._sup_enabled = bool(sup.get("enabled", True))
        self._heartbeat_s = float(sup.get("heartbeat_s", 5.0))
        self._wedge_timeout_s = float(sup.get("wedge_timeout_s", 5.0))
        self._backoff_base_s = float(sup.get("backoff_base_s", 0.5))
        self._backoff_max_s = float(sup.get("backoff_max_s", 15.0))
        self._max_respawns = int(sup.get("max_respawns", 3))
        self._spawn_timeout_s = float(sup.get("spawn_timeout_s", 600.0))
        self._stop_grace_s = float(sup.get("stop_grace_s", 30.0))
        # A life must survive this long to count as a recovery: without
        # it, a crash-LOOP (respawn succeeds, host dies seconds later)
        # would reset the failure counter every cycle and flap forever
        # instead of tripping the breaker.
        self._min_stable_s = float(sup.get("min_stable_s", 5.0))
        self._spawned_at: float | None = None
        self._supervisor: asyncio.Task | None = None
        self._host_down: asyncio.Event | None = None  # set by reader EOF
        self._down_reason = "crash"
        self._restarting = False
        self._restarts = 0
        self._respawn_failures = 0
        self._circuit_open = False
        # Provider hook, called (reason) the moment a host death/wedge is
        # being handled — the provider wires its flight-recorder dump
        # here so every restart leaves a debuggable artifact.
        self.on_host_restart = None
        # Measured host-pipe clock offset (host monotonic − provider
        # monotonic), from the startup clock handshake. On Linux both
        # processes read one CLOCK_MONOTONIC so it lands near zero — but
        # it is MEASURED, not assumed: host stamps are reconciled through
        # it instead of clamping negative cross-process spans to zero.
        self._clock_offset: float = 0.0
        # Admission capacity for the provider's overload shedding: the
        # engine serves `slots` streams concurrently; beyond
        # slots + max_queue, new requests would wait more than ~one slot
        # rotation, so the provider rejects them with a busy error.
        tpu = config.tpu
        self.slots = tpu.max_batch_size
        extra = tpu.max_queue if tpu.max_queue is not None else self.slots
        self.queue_limit = self.slots + max(0, extra)
        self.admission_ttft_bound_s = tpu.max_ttft_s
        # Relay-side emit accounting: host frames read vs events carried.
        # frames << events means the batched `events` protocol is doing
        # its job (one pipe read fans out a whole decode block).
        self.relay_stats = {"host_frames": 0, "host_events": 0,
                            "host_batched_frames": 0}
        # symledger fold (provider-fed): per-request cost blocks ride
        # the done chunks; the provider judges SLO attainment against
        # its configured targets and calls note_request_cost() with the
        # verdict. The autoscaler's goodput numerator counts ONLY
        # attained tokens — the raw relayed-event count it used before
        # stays exported as sym_autoscale_tokens_raw for continuity.
        self.ledger_stats = {"attained_tokens": 0, "raw_tokens": 0,
                             "device_s": 0.0, "requests": 0}
        # Stream resumption: the per-request emitted-token journal (what
        # each live stream has relayed — the death paths stamp `emitted`
        # from it into their restarting sheds, so a seeded resume knows
        # its RNG lane position) plus the relay-side resume ledger. The
        # host's own journal (stats-heartbeat "journal" rider) is merged
        # in as a lower bound each heartbeat.
        self._journal = ResumeJournal()
        self.resume_stats = {"resumes": 0, "resumed_tokens": 0,
                             "reused_tokens": 0, "dedup_dropped": 0}
        # Per-stage TTFT attribution (round-4 task #3: the ~2 s
        # engine→provider hop): each first event carries the host's
        # monotonic stage stamps ("t" field), and this side closes the
        # chain with its own submit/receipt stamps. All CLOCK_MONOTONIC —
        # one clock across processes on Linux.
        #   submit   provider stream start → host-pipe submit written
        #   pipe_in  submit written → host read + tokenized + enqueued
        #   queue    enqueued → entered a placement group
        #   prefill  placement pick → first token sampled
        #   emit     first token → host pipe write (block-flush hold)
        #   relay    host pipe write → this process relays the event
        from symmetry_tpu.utils.metrics import METRICS, MetricName
        from symmetry_tpu.utils.trace import Histogram

        self.stage_hists = {name: Histogram() for name in
                            ("submit", "pipe_in", "queue", "prefill",
                             "emit", "relay")}
        # Registry twins of the per-stage TTFT and relay accounting
        # (always-on time series in THIS process; the host's own
        # families arrive via the HostOp.METRICS probe, tier-labeled).
        self._m_stage = METRICS.histogram(
            MetricName.TTFT_STAGE,
            "per-stage TTFT attribution (submit/pipe_in/queue/prefill/"
            "emit/relay)", labels=("stage",))
        self._m_host_frames = METRICS.counter(
            MetricName.RELAY_HOST_FRAMES, "host-pipe frames relayed")
        self._m_host_events = METRICS.counter(
            MetricName.RELAY_HOST_EVENTS, "token events relayed")
        self._m_resume_wasted = METRICS.counter(
            MetricName.RESUME_WASTED_TOKENS,
            "overlap tokens the relay's resume offset-dedup dropped")

    def attach_slo_monitor(self, monitor: Any) -> None:
        """Provider hook: hand this backend the live SLO burn-rate
        monitor so the pool heartbeat can feed PoolRouter.update_gauges
        with real burn instead of the 0.0 the router defaults to. Safe
        to call in any mode; only pool mode reads it."""
        self._slo_monitor = monitor

    @property
    def _process_mode(self) -> bool:
        return getattr(self._config.tpu, "engine_isolation",
                       "process") == "process"

    @property
    def _local_pair(self) -> bool:
        """Disagg with BOTH tiers as local subprocesses (PR 7's shape);
        network mode replaces the prefill side with the handoff link,
        pool mode replaces BOTH sides with member sets."""
        return self._disagg and not self._net_mode and not self._pool_mode

    async def start(self) -> None:
        """Load weights and start the engine (may take minutes for large
        checkpoints; nothing here blocks the event loop)."""
        if self._started:
            return
        tpu_cfg = self._config.tpu
        role = getattr(tpu_cfg, "role", "unified") or "unified"
        if role in ("prefill", "decode"):
            raise BackendError(
                f"tpu.role {role!r} is a per-host tier role the disagg "
                f"broker assigns; a provider backend runs role unified "
                f"or disagg")
        if self._disagg and not self._process_mode:
            raise BackendError(
                "tpu.role: disagg requires engine_isolation: process "
                "(the two tiers are separate engine hosts)")
        mh = tpu_cfg.multihost
        if mh and mh.get("num_processes", 1) > 1 and mh.get("process_id", 0) != 0:
            # Refuse BEFORE joining the distributed job / loading weights —
            # a wrong-rank provider would become a dead participant the
            # other ranks hang on.
            raise BackendError(
                "only rank 0 runs the provider; start other ranks with "
                "`python -m symmetry_tpu.provider --worker`")
        if self._process_mode:
            await self._start_host_process()
        else:
            await self._start_inproc()
        self._started = True

    async def _start_inproc(self) -> None:
        from symmetry_tpu.utils.compile_cache import enable_compile_cache

        tpu_cfg = self._config.tpu
        mh = tpu_cfg.multihost
        enable_compile_cache(tpu_cfg)

        def build() -> InferenceEngine:
            return InferenceEngine.from_tpu_config(tpu_cfg)

        self._engine = await asyncio.to_thread(build)
        sched_engine = self._engine
        if mh and mh.get("num_processes", 1) > 1:
            # Rank 0 fronts the network; its scheduler drives all ranks in
            # lockstep through the command loop (parallel/multihost.py).
            from symmetry_tpu.parallel.multihost import (
                CommandLoop, MultihostEngine)

            self._command_loop = CommandLoop(self._engine,
                                             is_coordinator=True)
            sched_engine = MultihostEngine(self._command_loop)
        # Compile the decode program before taking traffic: the first
        # request must never stall every stream on a fresh XLA compile.
        await asyncio.to_thread(sched_engine.warmup)
        self._scheduler = Scheduler(
            sched_engine,
            pipeline_depth=int(getattr(tpu_cfg, "pipeline_depth", 2)))
        self._scheduler.start()
        log.info(
            f"tpu_native engine up (inproc): model={self._model_name} "
            f"slots={self._engine.max_slots} seq={self._engine.max_seq_len}")

    def _host_argv(self, cfg_path: str) -> list[str]:
        """Command line for the engine-host subprocess. A seam on purpose:
        the chaos suite substitutes a protocol-faithful fake host here to
        exercise crash/wedge/respawn without a JAX build per life."""
        import sys

        return [sys.executable, "-m", "symmetry_tpu.engine.host", cfg_path]

    async def _start_host_process(self) -> None:
        import tempfile

        import yaml

        cfg = {k: v for k, v in self._config.get_all().items()
               if k != "apiKey"}

        def write_cfg(d: dict) -> str:
            with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                             delete=False) as fh:
                yaml.safe_dump(d, fh)
                return fh.name

        if self._disagg:
            from symmetry_tpu.engine.disagg import derive_role_config

            # The decode tier is always the PRIMARY self._cfg_path
            # (stats/liveness target). The prefill config file exists
            # only for the local pair — in network mode the prefill
            # tier derives its own config on its own machine.
            self._cfg_path = write_cfg(derive_role_config(cfg, "decode"))
            if self._local_pair:
                self._prefill_cfg_path = write_cfg(
                    derive_role_config(cfg, "prefill"))
        else:
            self._cfg_path = write_cfg(cfg)
        self._host_down = asyncio.Event()
        if self._pool_mode:
            # Elastic pool: per-member readers and per-member
            # supervision replace the pair's single supervisor — a dead
            # member is a capacity event handled in its own domain.
            await self._start_pool()
            return
        await self._spawn_host()
        if self._net_mode:
            await self._start_link()
        if self._sup_enabled:
            self._supervisor = asyncio.get_running_loop().create_task(
                self._supervise())

    async def _spawn_one(self, cfg_path: str
                         ) -> asyncio.subprocess.Process:
        # readline() is bounded by the StreamReader limit (64 KiB
        # default) and raises past it, killing the reader task — which
        # the supervisor reads as a host death. 32 MiB fits the largest
        # non-disagg line (a full-ring {"op":"trace"} reply). A disagg
        # handoff frame is a single base64 line carrying a KV prefix —
        # ~128 KiB/token raw on an 8B model, so a 2048-token bucket
        # prefix is ~350 MB encoded; 1 GiB bounds that with headroom
        # (the limit is a cap, not an allocation).
        limit = (1 << 30) if self._disagg else 32 * 1024 * 1024
        return await asyncio.create_subprocess_exec(
            *self._host_argv(cfg_path),
            stdin=asyncio.subprocess.PIPE, stdout=asyncio.subprocess.PIPE,
            limit=limit)

    @staticmethod
    async def _await_ready(proc: asyncio.subprocess.Process,
                           what: str) -> None:
        """Read frames until the host's ready line (weight loading +
        warmup happen in the host before it appears)."""
        while True:
            line = await proc.stdout.readline()
            if not line:
                rc = await proc.wait()
                raise BackendError(f"{what} died during startup "
                                   f"(rc={rc})")
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if not isinstance(msg, dict):
                continue  # stray scalar on stdout (see _read_events)
            if msg.get("op") == HostOp.READY:
                return

    async def _spawn_host(self) -> None:
        """One host life: spawn, await ready, measure the clock offset,
        start the reader. Shared by first start and every respawn (the
        respawn reuses the same config file(s), so the persistent
        compile cache makes it a warm start). In disagg mode a "life"
        is the PAIR: both processes are created first so their engine
        builds overlap, then each is brought to ready."""
        self._host_dead = False
        self._engine_alive = True
        self._proc = await self._spawn_one(self._cfg_path)
        if self._local_pair:
            self._prefill_proc = await self._spawn_one(
                self._prefill_cfg_path)
        await self._await_ready(
            self._proc, "decode host" if self._disagg else "engine host")
        self._clock_offset = await self._clock_handshake(self._proc)
        self._reader = asyncio.get_running_loop().create_task(
            self._read_events())
        if self._local_pair:
            await self._await_ready(self._prefill_proc, "prefill host")
            self._prefill_clock_offset = await self._clock_handshake(
                self._prefill_proc)
            # The broker's wire-leg split maps the prefill host's
            # handoff emit stamps through this measured offset.
            self._broker.prefill_clock_offset = \
                self._prefill_clock_offset
            self._prefill_reader = asyncio.get_running_loop().create_task(
                self._read_prefill_events())
            log.info(
                f"tpu_native prefill host up "
                f"(pid {self._prefill_proc.pid}): clock_offset="
                f"{self._prefill_clock_offset * 1e6:+.0f}us")
        self._spawned_at = time.monotonic()
        log.info(f"tpu_native engine host up (pid {self._proc.pid}"
                 f"{', disagg pair' if self._disagg else ''}): "
                 f"model={self._model_name} "
                 f"clock_offset={self._clock_offset * 1e6:+.0f}us")

    # ------------------------------------------------- handoff link (net)

    async def _start_link(self) -> None:
        """Network-mode startup: optional inline PrefillNode, then the
        DecodeLink dial loop. A peer that is not up yet is NOT fatal —
        the link keeps reconnecting with backoff and submits shed
        retryable until it lands (static pairing means the operator
        brings the prefill machine up on its own schedule)."""
        from symmetry_tpu.engine.disagg.net import DecodeLink, LinkError

        peer = self._link_cfg.peer
        if self._link_cfg.inline:
            from symmetry_tpu.engine.disagg.node import PrefillNode

            self._inline_node = PrefillNode(self._config, listen=peer)
            await self._inline_node.start()
            # tcp://host:0 resolved to the real bound port.
            self._link_cfg.peer = self._inline_node.address
        self._link = DecodeLink(
            self._link_cfg,
            on_handoff=self._link_handoff,
            on_event=self._link_event,
            on_fail=self._link_fail,
            on_down=self._link_down)
        try:
            await self._link.start(
                wait_s=min(self._spawn_timeout_s, 120.0))
        except LinkError as exc:
            log.warning(f"{exc}; continuing — submits shed retryable "
                        f"until the link connects")

    async def _link_handoff(self, meta: dict, frame: bytes) -> None:
        """A complete, CRC-verified handoff frame off the link → the
        decode host's adopt path. Raising here naks the transfer (the
        sender retries); the ack only goes out after this returns, so
        the decode host's stdin write is inside the link's ack/credit
        backpressure loop."""
        import base64

        handoff = {"id": meta.get("id"), "p": int(meta.get("p", 0)),
                   "prompt_len": meta.get("prompt_len"),
                   "nbytes": len(frame),
                   "blocks": int(meta.get("blocks", 0)),
                   "shipped": int(meta.get("shipped", 0)),
                   "frame": base64.b64encode(frame).decode("ascii")}
        if "wire_s" in meta:
            handoff["wire_s"] = meta["wire_s"]
        adopt = self._broker.adopt_op(handoff, member="decode")
        if adopt is None:
            return  # request already cancelled/failed — drop the frame
        try:
            await self._host_send(adopt)
        except (ConnectionError, OSError):
            # The DECODE host's pipe failed (it is dying/respawning) —
            # a nak would make the sender retransmit the whole frame
            # at a problem that is local, and the retry would find the
            # broker entry already consumed and be ACKed as delivered
            # while adopting nothing. Ack the wire leg (it WAS
            # delivered intact) and shed the request retryable; the
            # host death path is about to shed every stream anyway.
            self._shed_request(
                str(meta.get("id", "")),
                "decode host unavailable for adoption")

    def _link_event(self, msg: dict) -> None:
        """Prefill-tier terminal events arriving over the link
        (tokenization/admission errors, deadline sheds) — same routing
        as the local pair's _read_prefill_events."""
        events = (msg.get("events")
                  if msg.get("op") == HostOp.EVENTS else [msg])
        if not isinstance(events, list):
            return
        for ev in events:
            if not isinstance(ev, dict):
                continue
            req_id = str(ev.get("id", ""))
            if ev.get("done"):
                self._broker.forget(req_id)
                if self._pool is not None:
                    self._pool.note_done(req_id)
                    self._pool_submits.pop(req_id, None)
            q = self._queues.get(req_id)
            if q is not None:
                q.put_nowait(ev)

    def _shed_request(self, req_id: str, error: str) -> None:
        """One in-flight request → the structured RETRYABLE restarting
        shed (clients fail over / retry; the link or tier that failed
        is already recovering). Stamped with the journal's emitted
        count, so pool re-placement and link-loss sheds carry the same
        resume anchor the supervisor's crash sheds do."""
        self._broker.forget(req_id)
        if self._pool is not None:
            self._pool.note_done(req_id)
            self._pool_submits.pop(req_id, None)
        q = self._queues.get(req_id)
        if q is not None:
            q.put_nowait({"op": HostOp.EVENT, "id": req_id, "text": "",
                          "done": True, "finish_reason": "error",
                          "restarting": True,
                          "emitted": self._journal.get(req_id),
                          "error": error})

    def _link_fail(self, req_id: str, reason: str) -> None:
        self._shed_request(
            req_id, f"handoff failed on the link: {reason or 'unknown'}")

    def _link_down(self, reason: str) -> None:
        """The handoff link died (cable pull, peer restart, injected
        drop): every migration still in flight is shed retryable —
        never hung — while already-adopted streams keep decoding and
        the DecodeLink reconnects with backoff."""
        for req_id in self._broker.shed_pending():
            self._shed_request(req_id, f"handoff link lost: {reason}")

    # ------------------------------------------------- elastic pool (M×N)

    def _node_factory(self, config: Any, listen: str):
        """Inline prefill-member constructor. A seam on purpose
        (mirrors _host_argv): tests substitute a PrefillNode subclass
        whose engine host is the protocol-faithful fake, so pool churn
        drills cost milliseconds instead of an engine build per node."""
        from symmetry_tpu.engine.disagg.node import PrefillNode

        return PrefillNode(config, listen=listen)

    @staticmethod
    def _member_listen_addr(base: str, index: int, count: int) -> str:
        """Per-member listen address for inline nodes. mem:// gets a
        suffix per member; tcp:// with more than one member rebinds to
        port 0 (each node resolves its real port at start)."""
        if base.startswith("mem://"):
            return f"{base}-p{index}"
        if base.startswith("tcp://") and count > 1:
            host = base[len("tcp://"):].rsplit(":", 1)[0]
            return f"tcp://{host}:0"
        return base

    async def _start_pool(self) -> None:
        """Pool-mode startup: N local decode members (each its own
        reader + supervision task), then M prefill members — inline
        self-hosted PrefillNodes and/or remote peers — each behind its
        own DecodeLink. A member that is not up yet is NOT fatal: it
        joins when it connects (hot-join), and until at least one
        prefill member is healthy submits shed retryable."""
        from symmetry_tpu.engine.disagg.autoscale import (
            AutoscaleConfig, PoolAutoscaler)
        from symmetry_tpu.engine.disagg.pool import PoolRouter

        tpu = self._config.tpu
        self._pool = PoolRouter(
            heartbeat_s=(self._pool_cfg.heartbeat_s
                         if self._pool_cfg.heartbeat_s > 0
                         else self._heartbeat_s),
            affinity_weight=float(
                getattr(tpu, "pool_affinity_weight", 1.0)))
        asc_cfg = AutoscaleConfig(getattr(tpu, "autoscale", None))
        if asc_cfg.enabled:
            # Remote prefill peers are machines this backend cannot
            # conjure — the prefill tier then stays fixed and only the
            # decode tier scales.
            self._autoscaler = PoolAutoscaler(
                asc_cfg, self._pool,
                grow_prefill=self._pool_cfg.prefill_peers is None)
        self._member_seq = {"prefill": self._pool_cfg.prefill_count,
                            "decode": self._pool_cfg.decode_count}
        self._pool_active = True
        members = [_DecodeMember(f"decode-{i}")
                   for i in range(self._pool_cfg.decode_count)]
        for m in members:
            self._decode_members[m.id] = m
            self._pool.add_member(m.id, "decode")
        # All member engine builds OVERLAP (a real host's weight load +
        # warmup takes minutes; N of them back-to-back would multiply
        # start() wall-clock by the pool size).
        await asyncio.gather(*[self._spawn_decode_member(m)
                               for m in members])
        for m in members:
            self._pool.mark_healthy(m.id)
            m.supervisor = asyncio.get_running_loop().create_task(
                self._supervise_decode_member(m))
            self._pool_tasks.append(m.supervisor)
        peers = self._pool_cfg.prefill_peers
        if peers is None:
            base = self._link_cfg.peer or "mem://disagg-pool"
            self._inline_nodes = [
                self._node_factory(self._config, self._member_listen_addr(
                    base, i, self._pool_cfg.prefill_count))
                for i in range(self._pool_cfg.prefill_count)]
            await asyncio.gather(*[node.start()
                                   for node in self._inline_nodes])
            peers = [node.address for node in self._inline_nodes]
            for i, node in enumerate(self._inline_nodes):
                self._node_by_member[f"prefill-{i}"] = node
        for i, addr in enumerate(peers):
            member_id = f"prefill-{i}"
            self._pool.add_member(member_id, "prefill", node_id=addr)
            await self._attach_prefill_link(member_id, addr)
        deadline = time.monotonic() + min(self._spawn_timeout_s, 120.0)
        while (self._pool.healthy_count("prefill") == 0
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        if self._pool.healthy_count("prefill") == 0:
            log.warning("pool: no prefill member connected yet; submits "
                        "shed retryable until one joins")
        self._pool_tasks.append(
            asyncio.get_running_loop().create_task(
                self._pool_heartbeat()))
        log.info(f"tpu_native pool up: "
                 f"{len(peers)}×prefill {self._pool_cfg.decode_count}"
                 f"×decode (inline nodes: {len(self._inline_nodes)})")

    async def _attach_prefill_link(self, member_id: str,
                                   addr: str) -> None:
        """Create + start one prefill member's DecodeLink (startup and
        autoscale-spawn share this): handoffs, events, and membership
        callbacks all member-scoped."""
        import functools

        from symmetry_tpu.engine.disagg.net import DecodeLink

        link = DecodeLink(
            self._link_cfg.for_peer(
                addr, heartbeat_s=self._pool_cfg.heartbeat_s),
            on_handoff=functools.partial(self._pool_handoff, member_id),
            on_event=self._link_event,
            on_fail=self._link_fail,
            on_down=functools.partial(self._pool_member_down, member_id),
            on_up=functools.partial(self._pool_member_up, member_id),
            on_drain=functools.partial(self._pool_member_drain,
                                       member_id),
            on_leave=functools.partial(self._pool_member_leave,
                                       member_id))
        self._plinks[member_id] = link
        await link.start()

    async def _spawn_decode_member(self, m: _DecodeMember) -> None:
        """One decode member life: spawn, ready, clock offset, reader —
        the member-scoped twin of _spawn_host."""
        m.dead = False
        m.engine_alive = True
        # Boot fence: spawned_at is None until READY lands, and the
        # heartbeat's wedge probe skips booting members — a host still
        # building/warming up cannot answer a stats probe, and killing
        # it for that turned every slow (loaded-machine) autoscale
        # spawn or respawn into a startup "wedge" (rc=-9).
        m.spawned_at = None
        m.proc = await self._spawn_one(self._cfg_path)
        await self._await_ready(m.proc, f"decode member {m.id}")
        m.clock_offset = await self._clock_handshake(m.proc)
        m.reader = asyncio.get_running_loop().create_task(
            self._read_member_events(m))
        m.spawned_at = time.monotonic()
        log.info(f"pool: decode member {m.id} up (pid {m.proc.pid}, "
                 f"clock_offset={m.clock_offset * 1e6:+.0f}us)")

    async def _read_member_events(self, m: _DecodeMember) -> None:
        """One decode member's pipe pump: same dispatch as _read_events
        but member-scoped — probe replies land in the MEMBER's waiters
        and EOF runs the MEMBER's death path, never the pool's."""
        proc = m.proc
        assert proc is not None and proc.stdout is not None
        while True:
            line = await proc.stdout.readline()
            if not line:
                break  # member host exited
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if not isinstance(msg, dict):
                continue
            op = msg.get("op")
            if op in (HostOp.STATS, HostOp.TRACE, HostOp.METRICS,
                      HostOp.PROFILE):
                if op == HostOp.STATS:
                    m.engine_alive = bool(msg.get("engine_alive", True))
                waiters, m.waiters[op] = m.waiters[op], []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.EVENTS:
                events = msg.get("events")
                if not isinstance(events, list):
                    continue
                self.relay_stats["host_frames"] += 1
                self.relay_stats["host_batched_frames"] += 1
                self.relay_stats["host_events"] += len(events)
                self._m_host_frames.inc()
                self._m_host_events.inc(len(events))
                for ev in events:
                    if not isinstance(ev, dict):
                        continue
                    q = self._queues.get(str(ev.get("id", "")))
                    if q is not None:
                        q.put_nowait(ev)
                continue
            if op != HostOp.EVENT:
                continue
            self.relay_stats["host_frames"] += 1
            self.relay_stats["host_events"] += 1
            self._m_host_frames.inc()
            self._m_host_events.inc()
            q = self._queues.get(str(msg.get("id", "")))
            if q is not None:
                q.put_nowait(msg)
        if not m.dead:  # natural EOF (a cancelled reader skips this)
            self._decode_member_lost(m, "decode member host exited")

    def _decode_member_lost(self, m: _DecodeMember, reason: str) -> None:
        """One decode member died: fail ONLY the streams adopted there
        (structured retryable — clients fail over while the member
        respawns), release its probe waiters, wake its supervisor. The
        other members keep serving untouched."""
        if m.dead:
            return
        m.dead = True
        if self._autoscaler is not None:
            # Churn, not a scaling decision: the autoscaler pauses
            # (cooldown) instead of mistaking respawn turbulence for
            # load and flapping the shape.
            self._autoscaler.note_churn()
        for req_id in self._pool.on_lost(m.id):
            self._shed_request(req_id, f"{reason} ({m.id})")
        for lst in m.waiters.values():
            for w in lst:
                if not w.done():
                    w.set_result(None)
            lst.clear()
        hook = self.on_host_restart
        if hook is not None:
            try:
                hook("crash")
            except Exception as exc:  # noqa: BLE001 — diagnostics only
                log.warning(f"on_host_restart hook failed: {exc}")
        m.down.set()

    async def _supervise_decode_member(self, m: _DecodeMember) -> None:
        """Per-member respawn loop: same backoff/stability/circuit
        rules as the pair supervisor, scoped to ONE member — its death
        never restarts a sibling."""
        import contextlib

        while self._pool_active and not m.circuit_open:
            await m.down.wait()
            m.down.clear()
            if not self._pool_active:
                return
            if (m.spawned_at is not None
                    and time.monotonic() - m.spawned_at
                    >= self._min_stable_s):
                m.respawn_failures = 0
            else:
                m.respawn_failures += 1
            if m.reader is not None:
                m.reader.cancel()
                m.reader = None
            if m.proc is not None:
                if m.proc.returncode is None:
                    with contextlib.suppress(ProcessLookupError):
                        m.proc.kill()
                with contextlib.suppress(Exception):
                    await m.proc.wait()
                m.proc = None
            while self._pool_active:
                if m.respawn_failures >= self._max_respawns:
                    m.circuit_open = True
                    log.error(f"pool: decode member {m.id} circuit "
                              f"breaker OPEN after "
                              f"{m.respawn_failures} consecutive "
                              f"failed lives")
                    return
                backoff = min(self._backoff_max_s,
                              self._backoff_base_s
                              * (2 ** min(m.respawn_failures, 8)))
                log.warning(f"pool: respawning decode member {m.id} in "
                            f"{backoff:.2f}s")
                await asyncio.sleep(backoff)
                if not self._pool_active:
                    return
                try:
                    await asyncio.wait_for(self._spawn_decode_member(m),
                                           self._spawn_timeout_s)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 — spawn failed
                    m.respawn_failures += 1
                    if m.proc is not None:
                        if m.proc.returncode is None:
                            with contextlib.suppress(ProcessLookupError):
                                m.proc.kill()
                        with contextlib.suppress(Exception):
                            await m.proc.wait()
                        m.proc = None
                    log.error(f"pool: decode member {m.id} respawn "
                              f"failed: {exc}")
                    continue
                m.restarts += 1
                pm = self._pool.get(m.id)
                if pm is not None:
                    pm.restarts = m.restarts
                self._pool.mark_healthy(m.id)
                log.warning(f"pool: decode member {m.id} respawned "
                            f"(restart #{m.restarts})")
                break

    async def _probe_member(self, m: _DecodeMember, op: str,
                            timeout: float = 10.0,
                            payload: dict | None = None) -> dict | None:
        if m.proc is None or m.dead:
            return None
        return await self._probe(op, m.waiters[op], m.proc, timeout,
                                 payload=payload)

    async def _pool_heartbeat(self) -> None:
        """Pool watchdog + gauge feed: probe each decode member's stats
        (wedge detection per member; queue-depth gauge for routing) and
        each connected prefill member's node stats (its host's queue
        depth as the placement signal). Link liveness itself is the
        DecodeLink ping/pong keepalive."""
        import contextlib

        period = (self._pool_cfg.heartbeat_s
                  if self._pool_cfg.heartbeat_s > 0 else self._heartbeat_s)
        while self._pool_active:
            await asyncio.sleep(period)
            if not self._pool_active:
                return
            # All probes CONCURRENT: one wedged member must not delay
            # the others' wedge detection (or stale their gauges) by a
            # full probe timeout each — per-member failure domains
            # apply to the watchdog too.
            decode = [m for m in self._decode_members.values()
                      if m.alive and m.spawned_at is not None]
            plinks = [(mid, link) for mid, link in self._plinks.items()
                      if link.connected]
            replies = await asyncio.gather(
                *[self._probe_member(m, HostOp.STATS,
                                     timeout=self._wedge_timeout_s)
                  for m in decode],
                *[link.probe(LinkOp.STATS,
                             timeout=self._wedge_timeout_s)
                  for _, link in plinks],
                return_exceptions=True)
            if not self._pool_active:
                return
            # Live SLO burn (provider monitor, fast window): the
            # members of this pool serve one provider, so the burn is a
            # provider-level signal — feeding it keeps the router's
            # tie-break (and symtop's per-member burn column) on real
            # request-stream data instead of a forever-0 placeholder,
            # and a multi-provider router comparing pools sees honest
            # numbers. None (no monitor attached / no SLO configured)
            # leaves the gauge untouched. The PER-SLO split feeds the
            # autoscaler (ttft → prefill tier, inter_chunk → decode).
            burns = (self._slo_monitor.burn_rates()
                     if self._slo_monitor is not None else None)
            burn = (max(burns.values(), default=0.0)
                    if burns is not None else None)
            # symprof's measured per-tier device cost: each member's
            # devprof.device_s_total rider, differenced per heartbeat —
            # the autoscaler's M:N ratio signal.
            busy = {"prefill": 0.0, "decode": 0.0}
            for m, msg in zip(decode, replies[:len(decode)]):
                if isinstance(msg, dict):
                    # Per-member journal rider: a member's death then
                    # stamps its streams' sheds with counts no staler
                    # than one pool heartbeat.
                    self._journal.merge(msg.get("journal"))
                    busy["decode"] += self._busy_delta(m.id, msg)
                if not isinstance(msg, dict) or not m.engine_alive:
                    if m.dead:
                        continue  # death path already ran
                    log.error(f"pool: decode member {m.id} wedged "
                              f"(no healthy stats reply); killing it")
                    if m.proc is not None and m.proc.returncode is None:
                        # Racing a self-exit between the check and the
                        # kill must not kill the WATCHDOG task.
                        with contextlib.suppress(ProcessLookupError):
                            m.proc.kill()  # reader EOF runs death path
                    continue
                # Gossip rider first: update_gauges stamps the gossip-
                # age gauge from the freshly-stored summary stamp.
                self._pool.update_summary(m.id, msg.get("prefix_summary"))
                self._pool.update_gauges(
                    m.id, queue_depth=msg.get("queue_depth"),
                    burn_rate=burn)
            for (member_id, _), reply in zip(plinks,
                                             replies[len(decode):]):
                host = (reply.get("host")
                        if isinstance(reply, dict) else None) or {}
                if isinstance(host, dict) \
                        and host.get("queue_depth") is not None:
                    busy["prefill"] += self._busy_delta(member_id, host)
                    self._pool.update_summary(
                        member_id, host.get("prefix_summary"))
                    self._pool.update_gauges(
                        member_id, queue_depth=host["queue_depth"],
                        burn_rate=burn)
            self._autoscale_tick(burns, busy)

    def _busy_delta(self, member_id: str, msg: dict) -> float:
        """One member's device-busy seconds since its last heartbeat,
        from the symprof stats rider (devprof.device_s_total, present
        when tpu.profile_sample > 0). A counter that went backwards is
        a host restart — the new life's total IS the delta."""
        dp = msg.get("devprof")
        if not isinstance(dp, dict):
            return 0.0
        try:
            total = float(dp.get("device_s_total") or 0.0)
        except (TypeError, ValueError):
            return 0.0
        prev = self._prev_busy.get(member_id)
        self._prev_busy[member_id] = total
        if prev is None:
            return max(total, 0.0)
        return total if total < prev else total - prev

    def note_request_cost(self, attained_tokens: int, raw_tokens: int,
                          device_s: float) -> None:
        """Provider fold hook: one finished request's SLO-attainment
        verdict plus its ledger-attributed device seconds. Feeds the
        autoscaler's goodput numerator — only tokens whose request met
        every configured SLO target count (a completion the client's
        deadline already discarded is cost, not goodput)."""
        ls = self.ledger_stats
        ls["attained_tokens"] += max(0, int(attained_tokens))
        ls["raw_tokens"] += max(0, int(raw_tokens))
        ls["device_s"] += max(0.0, float(device_s))
        ls["requests"] += 1

    def _autoscale_tick(self, burns: dict | None, busy: dict) -> None:
        """One controller step at the end of each pool heartbeat: feed
        the sensor snapshot, apply at most one decision as a background
        task (the heartbeat must keep probing while a spawn compiles),
        and book every non-hold decision where the flight recorder can
        see it."""
        if self._autoscaler is None or not self._pool_active:
            return
        applying = (self._scale_task is not None
                    and not self._scale_task.done())
        # Goodput numerator = SLO-attaining tokens from the provider's
        # per-request fold. The old numerator — raw relayed host events,
        # which counted deadline-missed and discarded tokens as goodput
        # — survives as the tokens_raw series so dashboards keep their
        # history while the headline switches to the honest count.
        # Until the first fold arrives (ledger off, or no request has
        # finished yet) fall back to the raw count rather than starving
        # the controller of a throughput signal.
        ls = self.ledger_stats
        raw = float(self.relay_stats["host_events"])
        attained = (float(ls["attained_tokens"]) if ls["requests"]
                    else raw)
        decision = self._autoscaler.tick(
            burn=burns, busy_delta_s=busy,
            tokens_total=attained,
            tokens_raw=raw,
            applying=applying)
        if decision["action"] == "hold":
            return
        log.info(f"autoscale: {decision['action']} — "
                 f"{decision['reason']} "
                 f"(goodput {decision['goodput_tokens_per_chip_s']} "
                 f"tok/chip-s at {decision['chip_s']} chip-s)")
        self._scale_task = asyncio.get_running_loop().create_task(
            self._apply_scale(decision))

    # --- autoscale actuators (member factory) -------------------------

    async def _apply_scale(self, decision: dict) -> None:
        """Turn one controller decision into member lifecycle events.
        Failures cool the controller down (note_churn) instead of
        retrying hot — the next tick re-evaluates from live sensors."""
        action = decision["action"]
        try:
            if action == "spawn":
                await self._scale_spawn(decision["tier"])
            elif action == "drain":
                await self._scale_drain(decision["tier"],
                                        decision["member"])
            elif action == "rebalance":
                # Grow first, shrink second: capacity never dips below
                # the pre-decision shape mid-rebalance.
                await self._scale_spawn(decision["spawn_tier"])
                await self._scale_drain(decision["drain_tier"],
                                        decision["member"])
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — scaling must not crash
            log.error(f"autoscale: applying {action} failed: {exc}")
            if self._autoscaler is not None:
                self._autoscaler.note_churn()

    async def _scale_spawn(self, tier: str) -> None:
        seq = self._member_seq.get(tier, 0)
        self._member_seq[tier] = seq + 1
        member_id = f"{tier}-{seq}"
        if tier == "decode":
            await self._grow_decode_member(member_id)
        else:
            await self._grow_prefill_member(member_id, seq)

    async def _grow_decode_member(self, member_id: str) -> None:
        """Autoscale spawn, decode tier: a fresh _DecodeMember with its
        own reader + supervision domain, exactly like a startup member."""
        import contextlib

        m = _DecodeMember(member_id)
        self._decode_members[member_id] = m
        self._pool.add_member(member_id, "decode")
        try:
            await asyncio.wait_for(self._spawn_decode_member(m),
                                   self._spawn_timeout_s)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — spawn failed
            log.error(f"autoscale: spawn of {member_id} failed: {exc}")
            if m.proc is not None:
                if m.proc.returncode is None:
                    with contextlib.suppress(ProcessLookupError):
                        m.proc.kill()
                with contextlib.suppress(Exception):
                    await m.proc.wait()
                m.proc = None
            self._decode_members.pop(member_id, None)
            self._pool.on_lost(member_id)
            self._pool.retire(member_id)
            raise
        self._pool.mark_healthy(member_id)
        m.supervisor = asyncio.get_running_loop().create_task(
            self._supervise_decode_member(m))
        self._pool_tasks.append(m.supervisor)
        log.info(f"autoscale: decode member {member_id} joined")

    async def _grow_prefill_member(self, member_id: str,
                                   index: int) -> None:
        """Autoscale spawn, prefill tier (inline nodes only — remote
        peers gate grow_prefill off): a fresh PrefillNode through the
        node factory, behind its own DecodeLink. The member goes
        healthy when the link's hello lands (_pool_member_up), same as
        a hot-join."""
        base = self._link_cfg.peer or "mem://disagg-pool"
        # count ≥ 2 forces a unique per-member address (mem:// suffix /
        # tcp port 0) — the original member may own the base address.
        listen = self._member_listen_addr(base, index, max(index + 1, 2))
        node = self._node_factory(self._config, listen)
        self._pool.add_member(member_id, "prefill")
        try:
            await node.start()
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — spawn failed
            log.error(f"autoscale: prefill node {member_id} failed to "
                      f"start: {exc}")
            self._pool.on_lost(member_id)
            self._pool.retire(member_id)
            raise
        self._inline_nodes.append(node)
        self._node_by_member[member_id] = node
        await self._attach_prefill_link(member_id, node.address)
        log.info(f"autoscale: prefill member {member_id} spawned at "
                 f"{node.address}")

    async def _scale_drain(self, tier: str, member_id: str) -> None:
        """Drain-before-kill: the router stops NEW placements (refusing
        the last placeable member — the 1×1 floor holds even if the
        controller mis-decides), in-flight work runs dry under the stop
        grace, then the member retires out of the registry for good."""
        ok = self._pool.drain(member_id)
        if not ok:
            log.warning(f"autoscale: drain of {member_id} refused "
                        f"(last placeable member of {tier})")
            return
        if tier == "decode":
            await self._retire_decode_member(member_id)
        else:
            await self._retire_prefill_member(member_id)

    async def _wait_drained(self, member_id: str) -> None:
        deadline = time.monotonic() + self._stop_grace_s
        while time.monotonic() < deadline:
            pm = self._pool.get(member_id)
            if pm is None or not pm.in_flight:
                return
            await asyncio.sleep(0.05)

    async def _retire_decode_member(self, member_id: str) -> None:
        import contextlib

        await self._wait_drained(member_id)
        m = self._decode_members.pop(member_id, None)
        self._prev_busy.pop(member_id, None)
        if m is None:
            return
        m.dead = True  # fence the reader's death path: deliberate stop
        if m.supervisor is not None:
            m.supervisor.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await m.supervisor
            if m.supervisor in self._pool_tasks:
                self._pool_tasks.remove(m.supervisor)
            m.supervisor = None
        if m.reader is not None:
            m.reader.cancel()
            m.reader = None
        if m.proc is not None:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": HostOp.SHUTDOWN},
                                      proc=m.proc)
            try:
                await asyncio.wait_for(m.proc.wait(), self._stop_grace_s)
            except asyncio.TimeoutError:
                m.proc.kill()
                await m.proc.wait()  # reap — no zombie
            m.proc = None
        if not self._pool.retire(member_id):
            # Grace expired with work still pinned there: shed it
            # structured-retryable (clients fail over) and retire.
            for req_id in self._pool.on_lost(member_id):
                self._shed_request(
                    req_id, f"decode member {member_id} scaled away")
            self._pool.retire(member_id)
        log.info(f"autoscale: decode member {member_id} retired")

    async def _retire_prefill_member(self, member_id: str) -> None:
        self._retiring.add(member_id)
        try:
            await self._wait_drained(member_id)
            link = self._plinks.pop(member_id, None)
            if link is not None:
                await link.stop()
            node = self._node_by_member.pop(member_id, None)
            if node is not None:
                await node.stop()
                if node in self._inline_nodes:
                    self._inline_nodes.remove(node)
            self._prev_busy.pop(member_id, None)
            if not self._pool.retire(member_id):
                ids = self._member_lost_ids(member_id)
                if ids:
                    self._spawn_replace(
                        ids, f"prefill member {member_id} scaled away")
                self._pool.retire(member_id)
            log.info(f"autoscale: prefill member {member_id} retired")
        finally:
            self._retiring.discard(member_id)

    # --- pool membership callbacks (link-driven) ----------------------

    def _pool_member_up(self, member_id: str) -> None:
        link = self._plinks.get(member_id)
        self._pool.mark_healthy(
            member_id,
            node_id=link.peer_node if link is not None else None)

    def _member_lost_ids(self, member_id: str) -> list[str]:
        """In-flight migrations on a lost member: the router's
        placement view unioned with the broker's pending-migration
        view (authoritative for submitted-but-not-adopted), so neither
        side's bookkeeping gap strands a request."""
        ids = set(self._pool.on_lost(member_id))
        ids.update(self._broker.pending_on(member_id))
        return sorted(ids)

    def _pool_member_down(self, member_id: str, reason: str) -> None:
        """Prefill member's link died (node death, cable pull, wedge):
        its in-flight migrations are RE-PLACED on a survivor — the shed
        only reaches the client when no survivor exists. The link keeps
        reconnecting; a successful reconnect is a rejoin."""
        if member_id in self._retiring:
            return  # deliberate retire tearing its own link down
        if self._autoscaler is not None:
            self._autoscaler.note_churn()
        ids = self._member_lost_ids(member_id)
        if ids:
            self._spawn_replace(ids, f"prefill member {member_id} lost: "
                                     f"{reason}")

    def _pool_member_drain(self, member_id: str, node: str) -> None:
        ok = self._pool.drain(member_id)
        if ok:
            log.info(f"pool: prefill member {member_id} "
                     f"({node or 'unnamed'}) draining")
        else:
            log.warning(f"pool: drain of prefill member {member_id} "
                        f"({node or 'unnamed'}) REFUSED — last placeable "
                        f"member of its tier")

    def _pool_member_leave(self, member_id: str, node: str) -> None:
        """Deliberate departure: account as churn; any straggler still
        in flight there is re-placed like a loss."""
        if member_id in self._retiring:
            return  # deliberate retire: the backend owns the teardown
        ids = self._member_lost_ids(member_id)
        log.info(f"pool: prefill member {member_id} "
                 f"({node or 'unnamed'}) left")
        if ids:
            self._spawn_replace(ids, f"prefill member {member_id} left")

    def _spawn_replace(self, ids: list[str], reason: str) -> None:
        task = asyncio.get_running_loop().create_task(
            self._pool_replace(ids, reason))
        self._replace_tasks.add(task)
        task.add_done_callback(self._replace_tasks.discard)

    async def _pool_replace(self, ids: list[str], reason: str) -> None:
        """Re-place lost in-flight migrations on survivors. Deadlines
        are NOT refunded (the broker keeps the original submit stamp);
        a request that cannot be re-placed sheds structured-retryable —
        the client fails over, nothing hangs, nothing fails outright."""
        for req_id in ids:
            if req_id not in self._queues:
                # Client already gone: just drop the migration state.
                self._pool_submits.pop(req_id, None)
                self._broker.forget(req_id)
                continue
            submit = self._pool_submits.get(req_id)
            placed = None
            if submit is not None:
                placed = await self._pool_send_submit(req_id, submit,
                                                      replacement=True)
            if placed is None:
                self._shed_request(req_id, reason)
            else:
                log.info(f"pool: re-placed {req_id} on {placed} "
                         f"after: {reason}")

    def _routing_digests(self, submit: dict) -> list[str] | None:
        """Causal block digests of a submit's prompt, computed
        provider-side with a routing tokenizer — the request half of
        the cache-affinity match (the member half is the gossiped
        summary). Tokenization here is deterministic and identical to
        the hosts' (same tokenizer files, pure chat template), so the
        digests are exactly the ones a member's radix tree gossips.
        None (load-only placement) on ANY failure: a routing hint must
        never take down a submit."""
        if self._route_tok is False:
            return None
        tpu = self._config.tpu
        if float(getattr(tpu, "pool_affinity_weight", 1.0)) <= 0.0:
            return None
        if self._route_tok is None:
            try:
                from symmetry_tpu.engine.tokenizer import get_tokenizer

                self._route_tok = get_tokenizer(
                    getattr(tpu, "tokenizer_path", None))
            except Exception as exc:  # noqa: BLE001 — degrade, never wedge
                log.warning(f"pool: routing tokenizer unavailable "
                            f"({exc}); placement stays load-only")
                self._route_tok = False
                return None
        try:
            from symmetry_tpu.engine.prefix_cache import block_digests

            ids = self._route_tok.apply_chat_template(
                submit.get("messages") or [])
            bs = int(getattr(tpu, "prefix_block_tokens", 16) or 16)
            # Same whole-block, suffix-keeps-one-token cap as the
            # engine's lookup: affinity should chase reachable KV.
            p = bs * ((len(ids) - 1) // bs)
            if p <= 0:
                return None
            return block_digests(ids, p, bs)
        except Exception:  # noqa: BLE001 — hint only
            return None

    async def _pool_send_submit(self, req_id: str, submit: dict,
                                *, replacement: bool = False
                                ) -> str | None:
        """Place + send one submit over a healthy member's link; walks
        the member set on send failure (each failed member excluded for
        this request — its own down path re-places the REST of its
        load). None when no healthy member accepted it. Placement is
        cache-affine (the request's block digests vs each member's
        gossiped summary), and the submit is stamped with the planned
        decode member + its ledger epoch so the prefill host keys its
        shipped-block ledger by the handoff's actual destination."""
        from symmetry_tpu.engine.disagg.net import LinkError

        digests = self._routing_digests(submit)
        planned = self._pool.plan_decode(req_id, digests)
        if planned is not None:
            submit["ledger"] = {
                "member": planned,
                "epoch": self._pool.ledger_epoch(planned)}
        else:
            submit.pop("ledger", None)
        exclude: set[str] = set()
        while True:
            member_id = self._pool.place(req_id, digests=digests,
                                         exclude=exclude)
            if member_id is None:
                return None
            link = self._plinks.get(member_id)
            if link is None or not link.connected:
                exclude.add(member_id)
                self._pool.release(req_id)
                continue
            try:
                await link.submit(submit)
            except (LinkError, ConnectionError, OSError):
                exclude.add(member_id)
                self._pool.release(req_id)
                continue
            # Only a DELIVERED submit counts as a placement (refused
            # members above must not inflate the ledger).
            self._pool.record_placement(req_id, replacement=replacement)
            self._broker.reassign(req_id, member_id)
            return member_id

    async def _pool_handoff(self, member_id: str, meta: dict,
                            frame: bytes) -> None:
        """A verified handoff frame off ONE member's link → the decode
        member the router picks by queue depth. Same ack semantics as
        the pair's _link_handoff: a local adoption failure sheds the
        request rather than nak the wire."""
        import base64

        req_id = str(meta.get("id", ""))
        if not self._broker.is_pending(req_id):
            # No pending migration: cancelled/failed — or a STALE
            # duplicate from a member that kept prefilling through a
            # link blip while the request was re-placed (and possibly
            # already adopted elsewhere). Only release THIS member's
            # placement, never the request's live decode adoption.
            if self._pool.assigned_to(req_id) == member_id:
                self._pool.release(req_id)
            return
        # Route the decode member BEFORE adopting so the broker can
        # book the frame into that member's ledger; the event loop is
        # single-threaded between the is_pending check and adopt_op, so
        # the pending entry cannot vanish underneath us.
        self._pool_submits.pop(req_id, None)
        decode_id = self._pool.route_decode(req_id)
        m = self._decode_members.get(decode_id) if decode_id else None
        if m is None or not m.alive:
            self._shed_request(
                req_id, "no decode member available for adoption")
            return
        handoff = {"id": meta.get("id"), "p": int(meta.get("p", 0)),
                   "prompt_len": meta.get("prompt_len"),
                   "nbytes": len(frame),
                   "blocks": int(meta.get("blocks", 0)),
                   "shipped": int(meta.get("shipped", 0)),
                   "frame": base64.b64encode(frame).decode("ascii")}
        if "wire_s" in meta:
            handoff["wire_s"] = meta["wire_s"]
        adopt = self._broker.adopt_op(handoff, member=decode_id)
        if adopt is None:
            return
        try:
            await self._host_send(adopt, proc=m.proc)
        except (ConnectionError, OSError):
            self._shed_request(
                req_id, f"decode member {m.id} unavailable for adoption")

    def _pool_status(self) -> dict:
        """The pool block for engine_stats(): router membership +
        per-link wire state + per-decode-host supervision."""
        st = self._pool.stats()
        st["links"] = {
            member_id: {"connected": link.connected,
                        "node": link.peer_node,
                        "connects": link.stats["connects"],
                        "drops": link.stats["drops"],
                        "wire_frames": link.stats["wire_frames"],
                        "wire_bytes": link.stats["wire_bytes"],
                        "clock_offset_s": round(link.clock_offset, 6)}
            for member_id, link in sorted(self._plinks.items())}
        st["decode_hosts"] = {
            m.id: {"alive": m.alive, "restarts": m.restarts,
                   "circuit_open": m.circuit_open,
                   "clock_offset_s": round(m.clock_offset, 6)}
            for m in self._decode_members.values()}
        st["inline_nodes"] = len(self._inline_nodes)
        if self._autoscaler is not None:
            st["autoscale"] = self._autoscaler.stats()
        return st

    async def _clock_handshake(self, proc: asyncio.subprocess.Process,
                               rounds: int = 5) -> float:
        """Measure one host's monotonic-clock offset before any traffic.

        Each round brackets the host's clock read between two local
        stamps; the min-RTT sample's NTP midpoint wins (utils/trace.
        clock_handshake_offset). Runs before that host's reader task
        exists, so replies are read directly off the pipe — nothing
        else can be writing yet (no requests submitted, stats only on
        demand)."""
        from symmetry_tpu.utils.trace import clock_handshake_offset

        samples: list[tuple[float, float, float]] = []
        for _ in range(rounds):
            t0 = time.monotonic()
            await self._host_send({"op": HostOp.CLOCK, "t0": t0}, proc=proc)
            while True:
                line = await proc.stdout.readline()
                if not line:
                    raise BackendError(
                        "engine host died during clock handshake")
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(msg, dict):
                    continue  # stray scalar on stdout (see _read_events)
                if msg.get("op") == HostOp.CLOCK and msg.get("t0") == t0:
                    samples.append((t0, float(msg["t"]), time.monotonic()))
                    break
        return clock_handshake_offset(samples)

    async def _read_events(self) -> None:
        proc = self._proc
        assert proc is not None and proc.stdout is not None
        while True:
            line = await proc.stdout.readline()
            if not line:
                break  # host exited
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if not isinstance(msg, dict):
                # Valid JSON but not a frame (a stray print of a number
                # or string on the host's stdout): ignoring it is cheap;
                # letting it raise would kill THIS reader task without
                # running the death path below — no stream would ever
                # be failed and no respawn would ever run.
                continue
            op = msg.get("op")
            if op == HostOp.STATS:
                # stats reply: liveness for the health loop + the full
                # scheduler breakdown for engine_stats() consumers
                self._engine_alive = bool(msg.get("engine_alive", True))
                waiters, self._stats_waiters = self._stats_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.TRACE:
                waiters, self._trace_waiters = self._trace_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.METRICS:
                waiters, self._metrics_waiters = self._metrics_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.PROFILE:
                # Capture-finished reply (arrives duration_s after the
                # request — the host runs it off its serve loop).
                waiters, self._profile_waiters = self._profile_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.EVENTS:
                # Batched frame: one pipe line carries every slot's delta
                # for a decode block. Fan out in frame order — per-request
                # (and cross-request) ordering is the list order.
                events = msg.get("events")
                if not isinstance(events, list):
                    continue
                self.relay_stats["host_frames"] += 1
                self.relay_stats["host_batched_frames"] += 1
                self.relay_stats["host_events"] += len(events)
                self._m_host_frames.inc()
                self._m_host_events.inc(len(events))
                for ev in events:
                    if not isinstance(ev, dict):
                        continue
                    q = self._queues.get(str(ev.get("id", "")))
                    if q is not None:
                        q.put_nowait(ev)
                continue
            if op != HostOp.EVENT:
                continue
            self.relay_stats["host_frames"] += 1
            self.relay_stats["host_events"] += 1
            self._m_host_frames.inc()
            self._m_host_events.inc()
            q = self._queues.get(str(msg.get("id", "")))
            if q is not None:
                q.put_nowait(msg)
        # Natural EOF only (a cancelled reader must NOT run this: during
        # a respawn the old task is cancelled, and firing the death path
        # then would fail streams served by the NEW host and re-trip the
        # supervisor against a healthy process).
        self._handle_host_exit("engine host exited")

    async def _read_prefill_events(self) -> None:
        """Prefill-host pipe pump (disagg only): forward handoff frames
        to the decode host as adopt ops, relay the prefill tier's OWN
        events (tokenization/admission errors, deadline sheds — terminal
        by construction, this tier never streams tokens), and feed its
        stats/trace probes. EOF runs the SAME death path as the decode
        host: the pair is one supervised unit."""
        proc = self._prefill_proc
        assert proc is not None and proc.stdout is not None
        while True:
            line = await proc.stdout.readline()
            if not line:
                break  # prefill host exited
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if not isinstance(msg, dict):
                continue
            op = msg.get("op")
            if op == HostOp.HANDOFF:
                adopt = self._broker.adopt_op(msg)
                if adopt is None:
                    continue  # request already cancelled/failed
                try:
                    await self._host_send(adopt)
                except (ConnectionError, OSError):
                    # Decode host dying mid-forward: its death path is
                    # about to shed every stream, this one included.
                    pass
                continue
            if op == HostOp.STATS:
                waiters, self._prefill_stats_waiters = (
                    self._prefill_stats_waiters, [])
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.TRACE:
                waiters, self._prefill_trace_waiters = (
                    self._prefill_trace_waiters, [])
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == HostOp.METRICS:
                waiters, self._prefill_metrics_waiters = (
                    self._prefill_metrics_waiters, [])
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op in (HostOp.EVENT, HostOp.EVENTS):
                events = (msg.get("events")
                          if op == HostOp.EVENTS else [msg])
                if not isinstance(events, list):
                    continue
                for ev in events:
                    if not isinstance(ev, dict):
                        continue
                    req_id = str(ev.get("id", ""))
                    if ev.get("done"):
                        # Terminal on the prefill tier: the migration
                        # will never happen — drop the pending state.
                        self._broker.forget(req_id)
                    q = self._queues.get(req_id)
                    if q is not None:
                        q.put_nowait(ev)
        self._handle_host_exit("prefill host exited")

    def _handle_host_exit(self, reason: str) -> None:
        """Shared reader-EOF death path. Idempotent per life: if the
        supervisor's heartbeat already handled this death (its
        returncode/dead-reader backstop runs _fail_streams and sets
        _host_down itself), a late EOF re-signaling the event would
        wake the supervisor a SECOND time after the respawn — counting
        a spurious stability failure and killing the healthy new host.
        In disagg mode EITHER host's EOF lands here; the respawn
        replaces the pair."""
        if self._host_dead:
            return
        # Fail every open stream — the host is gone — and wake the
        # supervisor. _host_dead also fences NEW streams (they would
        # otherwise register a queue nobody feeds and hang forever).
        self._host_dead = True
        self._fail_streams(reason)
        if self._host_down is not None:
            self._host_down.set()

    def _fail_streams(self, reason: str) -> None:
        """Terminal event into every open stream queue, and release any
        stats/trace probes awaiting a reply that will never come. With
        supervision on, the event is the structured RETRYABLE restarting
        shed (→ BackendRestartingError → provider {"restarting": true} →
        client ProviderRestartingError → failover); without it — or
        during a deliberate stop(), when no host is ever coming back —
        the old plain error."""
        restarting = (self._started and self._sup_enabled
                      and not self._circuit_open)
        for req_id, q in self._queues.items():
            q.put_nowait({"op": HostOp.EVENT, "done": True,
                          "finish_reason": "error",
                          "restarting": restarting,
                          # Journal-stamped emitted count: what this
                          # stream already relayed (host heartbeat
                          # journal merged in as a lower bound) — the
                          # resume's RNG-lane position rides the shed.
                          "emitted": self._journal.get(req_id),
                          "error": reason, "text": ""})
        for w in (self._stats_waiters + self._trace_waiters
                  + self._metrics_waiters + self._profile_waiters
                  + self._prefill_stats_waiters
                  + self._prefill_trace_waiters
                  + self._prefill_metrics_waiters):
            if not w.done():
                w.set_result(None)
        self._stats_waiters.clear()
        self._trace_waiters.clear()
        self._metrics_waiters.clear()
        # Profile waiters too: a capture in flight when the host dies
        # must fail fast like every other probe — its generous
        # duration+90s timeout would otherwise pin the provider's
        # single-flight capture slot for minutes after the host is gone.
        self._profile_waiters.clear()
        self._prefill_stats_waiters.clear()
        self._prefill_trace_waiters.clear()
        self._prefill_metrics_waiters.clear()
        if self._broker is not None:
            self._broker.fail_all()

    async def _host_send(self, obj: dict,
                         proc: asyncio.subprocess.Process | None = None
                         ) -> None:
        """Write one command line to a host's stdin (default: the
        primary/decode host)."""
        if proc is None:
            proc = self._proc
        if (proc is None or proc.stdin is None
                or getattr(proc.stdin, "is_closing", lambda: False)()):
            # Mid-respawn (or dead) host: surface as the connection error
            # every caller already suppresses/handles, never an assert.
            raise ConnectionError("engine host pipe unavailable")
        proc.stdin.write(
            (json.dumps(obj, separators=(",", ":")) + "\n").encode())
        await proc.stdin.drain()

    async def stop(self) -> None:
        import contextlib

        self._started = False
        if self._supervisor is not None:
            # Before touching the process: a mid-backoff supervisor must
            # not race this shutdown with a respawn.
            self._supervisor.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._supervisor
            self._supervisor = None
        self._restarting = False
        self._pool_active = False
        # Autoscale teardown first: a half-applied spawn/drain must not
        # race the member teardown below.
        if self._scale_task is not None:
            self._scale_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._scale_task
            self._scale_task = None
        self._retiring.clear()
        self._node_by_member.clear()
        self._prev_busy.clear()
        # Pool teardown first: member supervision and replace tasks
        # must not race the shutdown, and no handoff may land on a
        # decode member that is draining away.
        for task in self._pool_tasks + list(self._replace_tasks):
            task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await task
        self._pool_tasks.clear()
        self._replace_tasks.clear()
        for link in self._plinks.values():
            await link.stop()
        self._plinks.clear()
        for node in self._inline_nodes:
            await node.stop()
        self._inline_nodes.clear()
        for m in self._decode_members.values():
            m.dead = True  # fence the reader's death path: this is a stop
            if m.reader is not None:
                m.reader.cancel()
                m.reader = None
            if m.proc is not None:
                with contextlib.suppress(ConnectionError, OSError):
                    await self._host_send({"op": HostOp.SHUTDOWN},
                                          proc=m.proc)
                try:
                    await asyncio.wait_for(m.proc.wait(),
                                           self._stop_grace_s)
                except asyncio.TimeoutError:
                    m.proc.kill()
                    await m.proc.wait()  # reap — no zombie
                m.proc = None
        self._decode_members.clear()
        # Handoff link first (network mode): no new handoff may land on
        # a decode host that is about to drain. The inline node owns
        # its own prefill host shutdown.
        if self._link is not None:
            await self._link.stop()
            self._link = None
        if self._inline_node is not None:
            await self._inline_node.stop()
            self._inline_node = None
        # Prefill host first (disagg): it holds no streams, and stopping
        # it before the decode host means no handoff can land on a
        # half-shut pipe.
        if self._prefill_proc is not None:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": HostOp.SHUTDOWN},
                                      proc=self._prefill_proc)
            try:
                await asyncio.wait_for(self._prefill_proc.wait(),
                                       self._stop_grace_s)
            except asyncio.TimeoutError:
                self._prefill_proc.kill()
                await self._prefill_proc.wait()  # reap — no zombie
            self._prefill_proc = None
        if self._prefill_reader is not None:
            self._prefill_reader.cancel()
            self._prefill_reader = None
        if self._proc is not None:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": HostOp.SHUTDOWN})
            try:
                await asyncio.wait_for(self._proc.wait(),
                                       self._stop_grace_s)
            except asyncio.TimeoutError:
                self._proc.kill()
                await self._proc.wait()  # reap — no zombie
            self._proc = None
        if self._reader is not None:
            self._reader.cancel()
            self._reader = None
        for attr in ("_cfg_path", "_prefill_cfg_path"):
            path = getattr(self, attr)
            if path:
                import os

                with contextlib.suppress(OSError):
                    os.unlink(path)
                setattr(self, attr, None)
        if self._scheduler is not None:
            await asyncio.to_thread(self._scheduler.stop)
            if self._command_loop is not None:
                self._command_loop.stop()  # release worker ranks
                self._command_loop = None
            self._scheduler = None
            self._engine = None

    # ---------------------------------------------------------- supervisor

    async def _supervise(self) -> None:
        """Watchdog + respawn loop. Two wake sources: the reader's EOF
        event (crash — immediate) and the heartbeat tick (wedge — a live
        process whose stats op stops answering within wedge_timeout_s, or
        whose engine thread died). Detection kills the host; the reader's
        EOF path then fails in-flight streams and lands back here for the
        respawn."""
        while self._started and not self._circuit_open:
            try:
                await asyncio.wait_for(self._host_down.wait(),
                                       self._heartbeat_s)
            except asyncio.TimeoutError:
                # Heartbeat: probe a host that is nominally alive.
                if not self._started:
                    return
                proc = self._proc
                if proc is None or self._host_dead:
                    continue  # death already detected; EOF wakes us
                silent_death = (proc.returncode is not None
                                or self._reader is None
                                or self._reader.done())
                if self._local_pair and not silent_death:
                    # The pair is one unit: a dead prefill host/reader
                    # is the same failure as a dead decode one. (In
                    # network mode the prefill tier is supervised on
                    # ITS machine; the link owns that failure domain.)
                    pp = self._prefill_proc
                    silent_death = (pp is None or pp.returncode is not None
                                    or self._prefill_reader is None
                                    or self._prefill_reader.done())
                if silent_death:
                    # A process died or a reader task crashed WITHOUT
                    # the EOF path running (e.g. the reader hit an
                    # unexpected exception): nobody failed the streams or
                    # set _host_down, so waiting for it would spin this
                    # loop forever while clients hang. Run the death
                    # path here.
                    log.error("supervisor: host/reader died without EOF "
                              "handling; recovering")
                    self._host_dead = True
                    self._fail_streams("engine host reader failed")
                    self._kill_host_procs()
                    self._host_down.set()
                    continue
                msg = await self._probe_host_stats(
                    timeout=self._wedge_timeout_s)
                if isinstance(msg, dict):
                    # Emitted-token journal rider: the host's per-stream
                    # pipe-write counts, merged as a lower bound so the
                    # NEXT death's sheds stamp counts no staler than one
                    # heartbeat.
                    self._journal.merge(msg.get("journal"))
                alive = msg is not None and self._engine_alive
                if alive and self._local_pair and self._started:
                    # Decode tier answered — the prefill tier must too,
                    # with a LIVE scheduler thread (a wedged or engine-
                    # dead prefill host means every new request queues
                    # forever while active streams look healthy). Its
                    # engine_alive rides the probe reply directly; the
                    # reader only tracks the decode host's.
                    pmsg = await self._probe_prefill_stats(
                        timeout=self._wedge_timeout_s)
                    if pmsg is None:
                        msg = None  # prefill wedge
                        alive = False
                    elif not pmsg.get("engine_alive", True):
                        alive = False
                if not self._started:
                    return
                if alive:
                    continue
                self._down_reason = ("wedge" if msg is None
                                     else "engine_dead")
                log.error(
                    f"supervisor: host {self._down_reason} "
                    f"(pid {proc.pid}, no healthy stats reply within "
                    f"{self._wedge_timeout_s:.1f}s); killing it")
                self._kill_host_procs()
                continue  # reader EOF fails streams and sets _host_down
            self._host_down.clear()
            if not self._started or self._circuit_open:
                return
            await self._respawn_loop()

    async def _respawn_loop(self) -> None:
        """Respawn the dead host with exponential backoff; open the
        circuit breaker after max_respawns consecutive failures. A
        failure is a respawn that never reached ready OR a life that
        died before min_stable_s — only a STABLE life resets the count,
        so a crash-loop (spawn ok, die seconds later) walks the same
        backoff ladder into the breaker instead of flapping forever."""
        self._restarting = True
        reason, self._down_reason = self._down_reason, "crash"
        if (self._spawned_at is not None
                and time.monotonic() - self._spawned_at
                >= self._min_stable_s):
            self._respawn_failures = 0  # previous life proved stable
        else:
            self._respawn_failures += 1
            if self._respawn_failures >= self._max_respawns:
                self._circuit_open = True
                self._restarting = False
                log.error(
                    f"supervisor: circuit breaker OPEN — host died within "
                    f"{self._min_stable_s:.1f}s of spawn "
                    f"{self._respawn_failures} consecutive times; "
                    f"provider will deregister")
                return
        hook = self.on_host_restart
        if hook is not None:
            # Flight-recorder dump (provider-wired): the death must stay
            # debuggable even though we are about to paper over it.
            try:
                hook(reason)
            except Exception as exc:  # noqa: BLE001 — diagnostics only
                log.warning(f"on_host_restart hook failed: {exc}")
        try:
            while self._started:
                # Same formula as the retry_after_s hint clients get
                # (_restart_eta_s) — they must not desynchronize.
                backoff = self._restart_eta_s()
                log.warning(
                    f"supervisor: respawning engine host in {backoff:.2f}s"
                    f" (after {reason}; attempt"
                    f" {self._respawn_failures + 1})")
                await asyncio.sleep(backoff)
                if not self._started:
                    return
                await self._reap_host()
                try:
                    await asyncio.wait_for(self._spawn_host(),
                                           self._spawn_timeout_s)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 — any spawn failure
                    self._respawn_failures += 1
                    await self._reap_host()
                    if self._respawn_failures >= self._max_respawns:
                        self._circuit_open = True
                        log.error(
                            f"supervisor: circuit breaker OPEN after "
                            f"{self._respawn_failures} consecutive failed "
                            f"respawns ({exc}); provider will deregister")
                        return
                    log.error(
                        f"supervisor: respawn failed "
                        f"({self._respawn_failures}/{self._max_respawns}):"
                        f" {exc}")
                    continue
                self._restarts += 1
                # NOT resetting _respawn_failures here: the new life must
                # survive min_stable_s first (the reset happens on the
                # NEXT death's stability check — or never needs to).
                log.warning(
                    f"supervisor: engine host respawned "
                    f"(pid {self._proc.pid}, restart #{self._restarts})")
                return
        finally:
            self._restarting = False

    def _kill_host_procs(self) -> None:
        """SIGKILL whatever of the host pair is still running (reaping
        happens in _reap_host / the readers' EOF paths)."""
        import contextlib

        for proc in (self._proc, self._prefill_proc):
            if proc is not None and proc.returncode is None:
                with contextlib.suppress(ProcessLookupError):
                    proc.kill()

    async def _reap_host(self) -> None:
        """Tear down the current host life (dead or partial) so a fresh
        spawn starts clean: cancel the readers, kill and reap the
        process(es) — in disagg mode the pair is replaced together."""
        import contextlib

        for attr in ("_reader", "_prefill_reader"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                setattr(self, attr, None)
        for attr in ("_proc", "_prefill_proc"):
            proc = getattr(self, attr)
            setattr(self, attr, None)
            if proc is not None:
                if proc.returncode is None:
                    with contextlib.suppress(ProcessLookupError):
                        proc.kill()
                with contextlib.suppress(Exception):
                    await proc.wait()

    def _supervisor_stats(self) -> dict | None:
        if not (self._process_mode and self._sup_enabled):
            return None
        return {"restarts": self._restarts,
                "respawn_failures": self._respawn_failures,
                "restarting": self._restarting,
                "circuit_open": self._circuit_open}

    async def _probe(self, op: str, waiters: list,
                     proc: asyncio.subprocess.Process | None,
                     timeout: float,
                     payload: dict | None = None) -> dict | None:
        """One fresh op round-trip to a host; None on timeout/failure
        (a fire-and-forget probe would return the PREVIOUS probe's answer,
        delaying wedge detection by a health-loop period). `payload`
        rides extra command fields (the profile op's duration/dir)."""
        import contextlib

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        waiters.append(fut)
        try:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": op, **(payload or {})},
                                      proc=proc)
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            if fut in waiters:
                waiters.remove(fut)

    async def _probe_host_stats(self, timeout: float = 10.0) -> dict | None:
        return await self._probe(HostOp.STATS, self._stats_waiters, None,
                                 timeout)

    async def _probe_host_trace(self, timeout: float = 10.0) -> dict | None:
        return await self._probe(HostOp.TRACE, self._trace_waiters, None,
                                 timeout)

    async def _probe_host_metrics(self, timeout: float = 10.0
                                  ) -> dict | None:
        return await self._probe(HostOp.METRICS, self._metrics_waiters,
                                 None, timeout)

    async def _probe_prefill_stats(self, timeout: float = 10.0
                                   ) -> dict | None:
        if self._prefill_proc is None:
            return None
        return await self._probe(HostOp.STATS, self._prefill_stats_waiters,
                                 self._prefill_proc, timeout)

    async def _probe_prefill_metrics(self, timeout: float = 10.0
                                     ) -> dict | None:
        if self._prefill_proc is None:
            return None
        return await self._probe(HostOp.METRICS,
                                 self._prefill_metrics_waiters,
                                 self._prefill_proc, timeout)

    async def _probe_prefill_trace(self, timeout: float = 10.0
                                   ) -> dict | None:
        if self._prefill_proc is None:
            return None
        return await self._probe(HostOp.TRACE, self._prefill_trace_waiters,
                                 self._prefill_proc, timeout)

    async def trace_components(self) -> list[dict]:
        """Host + scheduler span rings, reconciled onto THIS process's
        clock: each component's clock_offset_s gains the measured
        host-pipe offset, so the provider's merge needs no knowledge of
        which process a span came from."""
        if self._process_mode and self._pool_mode:
            comps: list[dict] = []
            m0 = next((m for m in self._decode_members.values()
                       if m.alive), None)
            if m0 is not None:
                msg = await self._probe_member(m0, HostOp.TRACE)
                for comp in (msg or {}).get("components") or []:
                    if isinstance(comp, dict):
                        comps.append({
                            **comp, "clock_offset_s":
                                float(comp.get("clock_offset_s", 0.0))
                                + m0.clock_offset})
            comps.append(self._broker.tracer.component("handoff_link"))
            return comps
        if self._process_mode:
            if (self._proc is None or self._host_dead
                    or self._proc.returncode is not None):
                return []
            msg = await self._probe_host_trace()
            if msg is None:
                return []
            comps = []
            for comp in msg.get("components") or []:
                if isinstance(comp, dict):
                    comps.append({**comp, "clock_offset_s":
                                  float(comp.get("clock_offset_s", 0.0))
                                  + self._clock_offset})
            if self._disagg:
                # The prefill tier's rings too, on ITS measured offset,
                # with role-prefixed component names so the merged
                # timeline shows two distinct process rows (satellite
                # contract: per-role trace rows, not unified-mode ones).
                # In network mode the rings cross the LINK and the link
                # handshake offset reconciles the other MACHINE's clock.
                if self._net_mode:
                    link = self._link
                    pmsg = (await link.probe(LinkOp.TRACE)
                            if link is not None and link.connected
                            else None)
                    offset = link.clock_offset if link is not None else 0.0
                else:
                    pmsg = await self._probe_prefill_trace()
                    offset = self._prefill_clock_offset
                for comp in (pmsg or {}).get("components") or []:
                    if isinstance(comp, dict):
                        comps.append({
                            **comp,
                            "name": f"prefill_{comp.get('name', 'host')}",
                            "clock_offset_s":
                                float(comp.get("clock_offset_s", 0.0))
                                + offset})
                # The wire leg itself: one span per handoff frame,
                # already on THIS process's clock.
                comps.append(
                    self._broker.tracer.component("handoff_link"))
            return comps
        if self._scheduler is not None:
            trace_export = getattr(self._scheduler, "trace_export", None)
            if trace_export is not None:
                return [trace_export()]  # same process — offset 0
        return []

    async def capture_profile(self, duration_s: float = 2.0,
                              out_dir: str | None = None) -> dict:
        """On-demand jax.profiler capture on the serving engine
        (HostOp.PROFILE): process mode forwards to the primary host —
        the decode tier in disagg (where the steady-state decode loop
        lives), the first live member in pool mode — and awaits the
        capture-finished reply; inproc runs the capture in an executor
        thread against this process's devices. Returns {"path"} on
        success or {"error"} (capture already running, host down)."""
        payload = {"duration_s": float(duration_s),
                   **({"dir": out_dir} if out_dir else {})}
        # Generous beyond the window: the process's FIRST capture pays
        # the profiler's cold init (tens of seconds on a loaded host).
        timeout = float(duration_s) + 90.0
        if self._process_mode and self._pool_mode:
            m0 = next((m for m in self._decode_members.values()
                       if m.alive), None)
            if m0 is None:
                return {"error": "no live decode member"}
            msg = await self._probe_member(m0, HostOp.PROFILE,
                                           timeout=timeout,
                                           payload=payload)
            return ({k: v for k, v in msg.items() if k != "op"}
                    if msg is not None
                    else {"error": "profile probe failed (host down or timed out)"})
        if self._process_mode:
            if (self._proc is None or self._host_dead
                    or self._proc.returncode is not None):
                return {"error": "engine host is down"}
            msg = await self._probe(HostOp.PROFILE, self._profile_waiters,
                                    None, timeout, payload=payload)
            return ({k: v for k, v in msg.items() if k != "op"}
                    if msg is not None
                    else {"error": "profile probe failed (host down or timed out)"})
        # inproc: same process, same devices — capture right here, off
        # the event loop (the capture sleeps for its whole window).
        import tempfile

        from symmetry_tpu.utils.devprof import capture_device_profile

        target = out_dir or os.path.join(tempfile.gettempdir(),
                                         "symmetry_tpu_profiles")
        try:
            path = await asyncio.get_running_loop().run_in_executor(
                None, capture_device_profile, target, float(duration_s))
        except Exception as exc:  # noqa: BLE001 — reply, never raise
            return {"error": str(exc)}
        return {"path": path, "duration_s": float(duration_s)}

    async def metrics_snapshots(self) -> list[dict]:
        """The engine tier's metrics-registry snapshots, tier-labeled —
        merged by the provider into its Prometheus exposition and the
        peer-wire metrics reply (the per-tier labeling the disagg pair
        needs: symtop and a scrape can tell prefill from decode).

        inproc mode shares the provider's process registry, so the
        provider's own snapshot already covers the scheduler families —
        nothing extra to add. In network disagg mode the remote prefill
        node's registry lives on its machine (scrape it there); the
        link/broker families live in THIS process and ride the
        provider snapshot."""
        if not self._process_mode:
            return []
        if self._pool_mode:
            # Every live decode member, node-labeled — the per-member
            # series symtop's pool columns and a scrape read.
            members = [m for m in self._decode_members.values()
                       if m.alive]
            replies = await asyncio.gather(
                *[self._probe_member(m, HostOp.METRICS, timeout=5.0)
                  for m in members],
                return_exceptions=True)
            return [{"snapshot": {k: v for k, v in msg.items()
                                  if k not in ("op", "role")},
                     "labels": {"tier": "decode", "node": m.id}}
                    for m, msg in zip(members, replies)
                    if isinstance(msg, dict)]
        if (self._proc is None or self._host_dead
                or self._proc.returncode is not None):
            return []
        # Both tiers probed CONCURRENTLY with a short timeout: this
        # rides the stats wire reply, and stacking sequential 10 s probe
        # timeouts behind a wedged host would hold the peer loop far
        # longer than a scrape is worth.
        probes = [self._probe_host_metrics(timeout=5.0)]
        if self._local_pair:
            probes.append(self._probe_prefill_metrics(timeout=5.0))
        replies = await asyncio.gather(*probes, return_exceptions=True)
        out: list[dict] = []
        for i, msg in enumerate(replies):
            if not isinstance(msg, dict):
                continue
            role = str(msg.get("role")
                       or ("prefill" if i == 1 else "unified"))
            out.append({"snapshot": {k: v for k, v in msg.items()
                                     if k not in ("op", "role")},
                        "labels": {"tier": role}})
        return out

    async def engine_stats(self) -> dict | None:
        """The scheduler's serving breakdown (counters, engine-side TTFT,
        admission dispatch and block-interval percentiles) — surfaced
        through provider METRICS so a benchmark capture can attribute
        stalls to engine vs relay/wire (round-3 verdict #1/#3)."""
        if self._process_mode and self._pool_mode:
            return await self._pool_engine_stats()
        if self._process_mode:
            sup = self._supervisor_stats()
            if (self._proc is None or self._host_dead
                    or self._proc.returncode is not None):
                # Host down (mid-respawn or circuit open): the supervisor
                # block is the only engine-side truth there is.
                return {"supervisor": sup} if sup else None
            msg = await self._probe_host_stats()
            if msg is None:
                return {"supervisor": sup} if sup else None
            out = {k: v for k, v in msg.items() if k != "op"}
            out["relay"] = dict(self.relay_stats)
            out["resume"] = dict(self.resume_stats)
            if self.ledger_stats["requests"]:
                out["ledger_fold"] = dict(self.ledger_stats)
            out["clock_offset_s"] = round(self._clock_offset, 6)
            out["stages"] = {name: h.to_dict()
                             for name, h in self.stage_hists.items()
                             if h.count}
            if sup:
                out["supervisor"] = sup
            if self._disagg:
                # The handoff ledger (broker counters, prefill-tier
                # latency percentiles, the wire-leg split) and the
                # prefill host's own breakdown, nested so a capture can
                # attribute a slow TTFT to prefill-tier admission vs
                # handoff serialize vs WIRE vs decode-tier adoption —
                # the disagg analog of the stage hists.
                disagg: dict = self._broker.stats()
                if self._net_mode:
                    link = self._link
                    if link is not None:
                        reply = (await link.probe(LinkOp.STATS)
                                 if link.connected else None)
                        if reply:
                            host = reply.get("host")
                            if isinstance(host, dict):
                                disagg["prefill_host"] = {
                                    k: v for k, v in host.items()
                                    if k != "op"}
                            if isinstance(reply.get("node"), dict):
                                # Prefill-node-side link counters:
                                # sender retries, credit stalls/wall,
                                # handoffs pumped, host restarts.
                                disagg["node"] = reply["node"]
                        disagg["link"] = {
                            **link.stats,
                            "connected": link.connected,
                            "clock_offset_s": round(
                                link.clock_offset, 6),
                            **link.reassembly_stats}
                else:
                    pmsg = await self._probe_prefill_stats()
                    if pmsg is not None:
                        disagg["prefill_host"] = {
                            k: v for k, v in pmsg.items() if k != "op"}
                out["disagg"] = disagg
            return out
        if self._scheduler is None:
            return None
        stats = getattr(self._scheduler, "stats", None)
        out = (stats() if stats is not None
               else dict(self._scheduler.metrics))
        out["resume"] = dict(self.resume_stats)
        if self.ledger_stats["requests"]:
            out["ledger_fold"] = dict(self.ledger_stats)
        return out

    async def _pool_engine_stats(self) -> dict:
        """Pool-mode serving breakdown: the first live decode member's
        scheduler stats as the base (the familiar shape), the handoff
        ledger, and the pool block (membership, per-link wire state,
        per-member supervision) nested under disagg.pool."""
        members = list(self._decode_members.values())
        out: dict = {}
        m0 = next((m for m in members if m.alive), None)
        if m0 is not None:
            msg = await self._probe_member(m0, HostOp.STATS)
            if msg is not None:
                out = {k: v for k, v in msg.items() if k != "op"}
        out["relay"] = dict(self.relay_stats)
        out["resume"] = dict(self.resume_stats)
        if self.ledger_stats["requests"]:
            out["ledger_fold"] = dict(self.ledger_stats)
        out["stages"] = {name: h.to_dict()
                         for name, h in self.stage_hists.items()
                         if h.count}
        out["supervisor"] = {
            "restarts": sum(m.restarts for m in members),
            "respawn_failures": sum(m.respawn_failures for m in members),
            "restarting": any(not m.alive and not m.circuit_open
                              for m in members),
            "circuit_open": bool(members) and all(m.circuit_open
                                                  for m in members)}
        disagg: dict = self._broker.stats()
        disagg["pool"] = self._pool_status()
        out["disagg"] = disagg
        return out

    async def healthy(self) -> bool:
        """Engine liveness: a wedged decode loop must fail this (SURVEY §5.3
        — an engine wedge unregisters the provider). In SUPERVISED process
        mode, liveness authority moves to the watchdog: a crash or wedge
        mid-restart is a transient the supervisor is already handling, so
        this stays true and only the circuit breaker (max_respawns
        consecutive failed respawns) fails it — which is what deregisters
        the provider. Unsupervised process mode keeps the old semantics:
        a dead host, a dead engine thread, or a silent stats op all fail."""
        if self._process_mode:
            if self._pool_mode:
                # A pool is healthy while ANY decode member can still
                # come back: only every member's breaker opening (the
                # pool's capacity is permanently gone) deregisters.
                members = list(self._decode_members.values())
                return (self._started and bool(members)
                        and not all(m.circuit_open for m in members))
            if not self._started or self._circuit_open:
                return False
            if self._sup_enabled:
                return True
            if (self._proc is None or self._host_dead
                    or self._proc.returncode is not None):
                return False
            if self._local_pair and (
                    self._prefill_proc is None
                    or self._prefill_proc.returncode is not None):
                return False
            if await self._probe_host_stats() is None:
                return False
            return self._engine_alive
        if self._engine is None or self._scheduler is None:
            return False
        thread = self._scheduler._thread
        return thread is not None and thread.is_alive()

    def _chunk_line(self, request_id: str, created: int, delta: dict,
                    finish: str | None = None) -> str:
        payload = {
            "id": request_id,
            "object": "chat.completion.chunk",
            "created": created,
            "model": self._model_name,
            "choices": [{"index": 0, "delta": delta,
                         "finish_reason": finish}],
        }
        return f"data: {json.dumps(payload)}"

    async def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        if not self._started:
            raise BackendError("tpu_native backend not started")
        max_new = (request.max_tokens if request.max_tokens is not None
                   else DEFAULT_MAX_NEW_TOKENS)
        if max_new < 1:
            raise BackendError(f"max_tokens must be >= 1, got {max_new}")
        request_id = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        created = int(time.time())

        if self._process_mode:
            async for chunk in self._stream_host(request, request_id,
                                                 created, max_new):
                yield chunk
            return

        engine = self._engine
        try:
            prompt_ids = engine.tokenizer.apply_chat_template(request.messages)
        except Exception as exc:  # tokenizer/template failure
            raise BackendError(f"tokenization failed: {exc}") from exc
        sampling = SamplingParams.from_request(request)
        resume_offset = 0
        if request.resume_text is not None:
            # In-process resume: same semantics as the host's _submit
            # (resolve_resume — the shared implementation): condition on
            # prompt + the client's received text, offset the budget,
            # fast-forward the seeded RNG lane. Without this,
            # supports_resume=True would let the provider accept a
            # resume this branch then serves from token 0 — splicing a
            # duplicate completion onto the client's partial text.
            import dataclasses

            from symmetry_tpu.engine.tokenizer import resolve_resume

            try:
                prompt_ids, max_new, resume_offset = resolve_resume(
                    engine.tokenizer,
                    {"text": request.resume_text,
                     **({"tokens": request.resume_tokens}
                        if request.resume_tokens is not None else {})},
                    prompt_ids, max_new)
            except Exception as exc:  # noqa: BLE001
                raise BackendError(f"resume failed: {exc}") from exc
            sampling = dataclasses.replace(sampling,
                                           rng_skip=resume_offset)
            self.resume_stats["resumes"] += 1
            self.resume_stats["resumed_tokens"] += resume_offset
            if max_new == 0:
                # Budget already spent by the interrupted stream — only
                # the finish frame was lost; complete without admitting.
                yield StreamChunk(
                    raw=self._chunk_line(request_id, created,
                                         {"role": "assistant"}), text="")
                yield StreamChunk(
                    raw=self._chunk_line(request_id, created, {},
                                         finish="length"), text="")
                yield StreamChunk(raw="data: [DONE]", text="", done=True)
                return

        if FAULTS.enabled and await FAULTS.apoint("backend.dispatch"):
            raise BackendError("injected frame drop at backend.dispatch")
        session = AsyncSession(self._scheduler,
                               loop=asyncio.get_running_loop())
        session.submit(prompt_ids, sampling,
                       max_new, request_id=request_id,
                       speculative=request.speculative,
                       trace_id=request.trace_id,
                       deadline_s=request.deadline_s,
                       resume_offset=resume_offset)

        def chunk_line(delta: dict, finish: str | None = None) -> str:
            return self._chunk_line(request_id, created, delta, finish)

        try:
            yield StreamChunk(raw=chunk_line({"role": "assistant"}), text="")
            reported = 0
            async for ev in session.events():
                if ev.finish_reason == "expired":
                    raise BackendDeadlineError(
                        ev.error or "request deadline expired")
                if ev.error is not None:
                    raise BackendError(ev.error)
                if ev.text:
                    # exact token accounting: tokens_emitted is the
                    # cumulative streamed-token count, a block chunk
                    # carries the delta (EOS and discarded post-finish
                    # tokens never appear in it)
                    n_new = max(ev.tokens_emitted - reported, 0)
                    reported = max(ev.tokens_emitted, reported)
                    yield StreamChunk(raw=chunk_line({"content": ev.text}),
                                      text=ev.text, tokens=n_new)
                if ev.done:
                    yield StreamChunk(
                        raw=chunk_line({}, finish=ev.finish_reason or "stop"),
                        text="")
                    # symledger: the scheduler's finish event carries the
                    # request's attributed cost block; ride it out on the
                    # terminal chunk so the provider folds per-request
                    # device time / waste / goodput. None while
                    # tpu.ledger is off.
                    yield StreamChunk(raw="data: [DONE]", text="",
                                      done=True, costs=ev.costs)
        finally:
            session.cancel()  # no-op if complete; frees the slot if client left

    def _observe_stages(self, t_recv: float, t_submit: float,
                        t: dict, clock_offset: float | None = None
                        ) -> None:
        """Fold one request's first-event stage stamps into the per-stage
        TTFT histograms.

        Host stamps are mapped onto THIS process's clock through the
        measured handshake offset (host − provider) before differencing —
        the old code assumed zero offset and clamped the resulting
        negative cross-process spans to zero, which silently zeroed the
        pipe_in/relay legs whenever clock reads interleaved. Spans are
        recorded as measured: residual sub-RTT jitter may still produce a
        microsecond-negative value, and hiding it would misstate the
        distribution the same way the clamp did."""
        now = time.monotonic()
        off = (self._clock_offset if clock_offset is None
               else clock_offset)
        recv = t["recv"] - off if "recv" in t else t_submit
        picked = t["picked"] - off if "picked" in t else recv
        first = t["first"] - off if "first" in t else picked
        out = t["out"] - off if "out" in t else first
        spans = {"submit": t_submit - t_recv,
                 "pipe_in": recv - t_submit,
                 "queue": picked - recv,
                 "prefill": first - picked,
                 "emit": out - first,
                 "relay": now - out}
        for name, span in spans.items():
            self.stage_hists[name].observe(span)
            self._m_stage.observe(span, stage=name)

    def _restart_eta_s(self) -> float:
        """Rough time until the host is back — the retry_after hint on
        restarting sheds (next respawn backoff; spawn time not included)."""
        return min(self._backoff_max_s,
                   self._backoff_base_s
                   * (2 ** min(self._respawn_failures, 8)))

    def _check_host_available(self) -> None:
        """Fence for new work against a down host: circuit-open is
        permanent (plain BackendError → provider error path), a
        supervised death/respawn window is the retryable restarting shed."""
        if self._pool_mode:
            members = list(self._decode_members.values())
            if members and all(m.circuit_open for m in members):
                raise BackendError(
                    "every decode pool member's circuit breaker is open")
            if not any(m.alive for m in members):
                raise BackendRestartingError(
                    "decode pool members restarting",
                    retry_after_s=self._restart_eta_s())
            # Prefill availability is a PLACEMENT decision — the submit
            # path sheds retryable when no member is placeable.
            return
        if self._circuit_open:
            raise BackendError(
                "engine host unavailable (circuit breaker open)")
        down = (self._restarting or self._host_dead or self._proc is None
                or self._proc.returncode is not None)
        if not down and self._local_pair:
            down = (self._prefill_proc is None
                    or self._prefill_proc.returncode is not None)
        if down:
            if self._sup_enabled:
                raise BackendRestartingError(
                    "engine host restarting",
                    retry_after_s=self._restart_eta_s())
            raise BackendError("engine host exited")
        if self._net_mode and (self._link is None
                               or not self._link.connected):
            # Link down is ALWAYS a retryable shed (the reconnect loop
            # is already running), independent of host supervision.
            raise BackendRestartingError(
                "handoff link down (reconnecting)",
                retry_after_s=self._link_cfg.reconnect_base_s * 2)

    async def _stream_host(self, request: InferenceRequest, request_id: str,
                           created: int, max_new: int
                           ) -> AsyncIterator[StreamChunk]:
        """Host-process path: submit over the pipe, relay its events."""
        self._check_host_available()
        if FAULTS.enabled and await FAULTS.apoint("backend.dispatch"):
            raise BackendError("injected frame drop at backend.dispatch")
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = queue
        completed = False
        t_recv = time.monotonic()
        # Journal entry for this stream (released on every exit path):
        # the death paths stamp their sheds' `emitted` counts from it.
        journal = self._journal.track(request_id)
        is_resume = request.resume_text is not None
        if is_resume:
            self.resume_stats["resumes"] += 1
            if request.resume_tokens:
                self.resume_stats["resumed_tokens"] += request.resume_tokens
        # Offset dedup (armed by the first event's resume_from): events
        # whose tokens the client already holds are dropped here at the
        # relay, so a resume never replays received tokens even when the
        # serving host floored its continuation below the client's count.
        drop_left: int | None = None
        dedup_dropped = 0  # tokens dropped here → resume_discarded waste
        try:
            try:
                submit = {
                    "op": HostOp.SUBMIT, "id": request_id,
                    "messages": request.messages, "max_new": max_new,
                    "sampling": {"temperature": request.temperature or 0.0,
                                 "top_p": (request.top_p
                                           if request.top_p is not None
                                           else 1.0),
                                 "top_k": getattr(request, "top_k", None)
                                 or 0,
                                 "seed": request.seed},
                    **({"speculative": request.speculative}
                       if request.speculative is not None else {}),
                    **({"trace": request.trace_id}
                       if request.trace_id else {}),
                    **({"deadline_s": request.deadline_s}
                       if request.deadline_s is not None else {}),
                    **({"resume": {
                            "text": request.resume_text,
                            **({"tokens": int(request.resume_tokens)}
                               if request.resume_tokens is not None
                               else {})}}
                       if is_resume else {})}
                if self._disagg:
                    # Disagg: new work enters through the PREFILL tier;
                    # the broker keeps the state the decode tier will
                    # need when the handoff frame comes back. Network
                    # mode sends the submit over the handoff link (a
                    # LinkError is a ConnectionError — the handler
                    # below turns it into the retryable shed). Pool
                    # mode PLACES it on the least-loaded healthy
                    # member and keeps the full op for re-placement.
                    self._broker.note_submit(request_id, submit)
                    if self._pool_mode:
                        self._pool_submits[request_id] = submit
                        member = await self._pool_send_submit(
                            request_id, submit)
                        if member is None:
                            self._pool_submits.pop(request_id, None)
                            self._broker.forget(request_id)
                            raise BackendRestartingError(
                                "no healthy prefill pool member",
                                retry_after_s=(
                                    self._link_cfg.reconnect_base_s * 2))
                    elif self._net_mode:
                        # Stamp the decode-side ledger epoch: a decode
                        # host respawn dropped its KV, so the prefill
                        # host must forget which blocks it shipped.
                        submit["ledger"] = {"member": "decode",
                                            "epoch": self._restarts}
                        await self._link.submit(submit)
                    else:
                        await self._host_send(submit,
                                              proc=self._prefill_proc)
                else:
                    await self._host_send(submit)
            except (ConnectionError, OSError):
                # The host died between the fence and the write (the
                # reader may not have processed the EOF yet, so the
                # re-check can still see a nominally-live host): same
                # contract as a mid-stream death — retryable whenever
                # the supervisor will bring the host back.
                self._check_host_available()
                if self._sup_enabled:
                    raise BackendRestartingError(
                        "engine host pipe write failed (host dying)",
                        retry_after_s=self._restart_eta_s()) from None
                raise BackendError("engine host pipe write failed") from None
            t_submit = time.monotonic()
            yield StreamChunk(
                raw=self._chunk_line(request_id, created,
                                     {"role": "assistant"}), text="")
            while True:
                # Generous ceiling: even a deep chunked prefill emits
                # within minutes; a host that is alive-but-wedged would
                # otherwise hang this stream forever (health checks
                # deregister the provider, but open streams must end too).
                try:
                    ev = await asyncio.wait_for(queue.get(), 600)
                except asyncio.TimeoutError:
                    raise BackendError(
                        "engine host produced no event for 600s") from None
                stamps = ev.get("t")
                if isinstance(stamps, dict):
                    off = None
                    if self._pool_mode:
                        # Host stamps came from whichever decode member
                        # adopted this request — reconcile through ITS
                        # measured clock offset.
                        dm = self._decode_members.get(
                            self._pool.adopted_on(request_id) or "")
                        if dm is not None:
                            off = dm.clock_offset
                    self._observe_stages(t_recv, t_submit, stamps,
                                         clock_offset=off)
                if "reused" in ev:
                    # First-event rider: radix tokens this admission
                    # reused (for a resume, the cheap-seeded-re-prefill
                    # contract the chaos round asserts on).
                    if is_resume:
                        self.resume_stats["reused_tokens"] += int(
                            ev.get("reused") or 0)
                        if request.resume_tokens is None:
                            # Hard-drop resumes carry no claimed count —
                            # the host derived it from the text and
                            # echoes it as resume_from; book it so the
                            # wasted-work headline counts this failure
                            # class too.
                            self.resume_stats["resumed_tokens"] += int(
                                ev.get("resume_from") or 0)
                    if is_resume and drop_left is None:
                        # Arm the offset dedup: the host continued from
                        # resume_from (its token numbering == the
                        # client's claimed count when one was sent);
                        # anything below the client's count is overlap.
                        server_from = ev.get("resume_from")
                        if (request.resume_tokens is not None
                                and isinstance(server_from, int)):
                            drop_left = max(
                                0, request.resume_tokens - server_from)
                err = ev.get("error")
                if ev.get("restarting"):
                    # Host crash/wedge mid-stream: the structured
                    # RETRYABLE shed (supervisor is respawning; the
                    # client should fail over now, not wait). Carries
                    # the journal-stamped emitted count — the resume's
                    # RNG-lane anchor.
                    emitted = ev.get("emitted")
                    raise BackendRestartingError(
                        err or "engine host restarting",
                        retry_after_s=self._restart_eta_s(),
                        emitted=(int(emitted)
                                 if isinstance(emitted, int) else None))
                if ev.get("finish_reason") == "expired":
                    raise BackendDeadlineError(
                        err or "request deadline expired")
                if err and ev.get("finish_reason") == "error":
                    raise BackendError(err)
                text = ev.get("text", "")
                n_new = int(ev.get("tokens_new", 0))
                if text and drop_left:
                    if n_new <= drop_left:
                        # Overlap: the client already has these tokens —
                        # drop the text (a resume never replays tokens
                        # the client received). A done=True event still
                        # delivers its finish below: swallowing it would
                        # hang the stream on a queue nobody feeds.
                        drop_left -= n_new
                        dedup_dropped += n_new
                        self.resume_stats["dedup_dropped"] += n_new
                        self._m_resume_wasted.inc(n_new)
                        if not ev.get("done"):
                            continue
                        text = ""
                    else:
                        # Straddling block event: token-to-text
                        # boundaries inside one event are not
                        # recoverable here, and relaying it whole would
                        # splice already-received characters into the
                        # client transcript — silent corruption. Fail
                        # the RESUME attempt cleanly instead: the
                        # client's fallback regenerates from scratch,
                        # which is slower but byte-correct.
                        raise BackendError(
                            f"resume overlap straddles a block event "
                            f"({n_new} tokens, {drop_left} left to "
                            f"drop) — cannot dedup at token "
                            f"granularity; restart the stream")
                if text:
                    journal.note(n_new)
                    yield StreamChunk(
                        raw=self._chunk_line(request_id, created,
                                             {"content": text}),
                        text=text, tokens=n_new)
                if ev.get("done"):
                    completed = True
                    yield StreamChunk(
                        raw=self._chunk_line(
                            request_id, created, {},
                            finish=ev.get("finish_reason") or "stop"),
                        text="")
                    costs = ev.get("costs")
                    if isinstance(costs, dict) and dedup_dropped:
                        # Relay-side dedup discarded tokens the device
                        # already paid for: price them at this request's
                        # own decode rate and book resume_discarded —
                        # the scheduler cannot see this class (the drop
                        # happens here), so the relay is its one true
                        # booking site. Mutating the relayed block is
                        # safe: it crossed the pipe, nothing else holds
                        # a reference.
                        dev = costs.get("device_s") or {}
                        toks = int(costs.get("tokens") or 0)
                        rate = (float(dev.get("decode", 0.0))
                                / toks if toks > 0 else 0.0)
                        wasted = costs.setdefault("wasted_s", {})
                        wasted["resume_discarded"] = round(
                            wasted.get("resume_discarded", 0.0)
                            + rate * dedup_dropped, 6)
                        costs["wasted_total_s"] = round(
                            sum(wasted.values()), 6)
                        costs["wasted_tokens"] = int(
                            costs.get("wasted_tokens") or 0) + dedup_dropped
                    yield StreamChunk(
                        raw="data: [DONE]", text="", done=True,
                        costs=costs if isinstance(costs, dict) else None)
                    return
        finally:
            # Journal release AFTER the stream settles: every death path
            # that stamps from it ran synchronously before this task
            # resumed, so the count was read while still tracked.
            journal.release()
            self._queues.pop(request_id, None)
            if self._pool_mode:
                placed = self._pool.assigned_to(request_id)
                adopted = self._pool.adopted_on(request_id)
                self._pool.note_done(request_id)
                self._pool_submits.pop(request_id, None)
                if not completed:
                    import contextlib

                    self._broker.forget(request_id)
                    # Cancel wherever the request may still live: the
                    # prefill member it was placed on (over its link)
                    # and the decode member that adopted it.
                    link = self._plinks.get(placed) if placed else None
                    if link is not None:
                        with contextlib.suppress(ConnectionError, OSError):
                            await link.cancel(
                                {"op": HostOp.CANCEL, "id": request_id})
                    dm = (self._decode_members.get(adopted)
                          if adopted else None)
                    if dm is not None and dm.alive:
                        with contextlib.suppress(ConnectionError, OSError):
                            await self._host_send(
                                {"op": HostOp.CANCEL, "id": request_id},
                                proc=dm.proc)
            elif not completed:
                # client abandoned the stream: free the slot host-side.
                # In disagg the request may be on EITHER tier (queued or
                # prefilling on one, decoding on the other) — cancel on
                # both; the hosts ignore ids they don't hold.
                import contextlib

                if self._broker is not None:
                    self._broker.forget(request_id)
                if self._net_mode and self._link is not None:
                    # The request may still be queued/prefilling on the
                    # REMOTE tier — cancel travels the link.
                    with contextlib.suppress(ConnectionError, OSError):
                        await self._link.cancel(
                            {"op": HostOp.CANCEL, "id": request_id})
                for proc in (self._proc, self._prefill_proc):
                    if proc is None or proc.returncode is not None:
                        continue
                    with contextlib.suppress(ConnectionError, OSError):
                        await self._host_send(
                            {"op": HostOp.CANCEL, "id": request_id}, proc=proc)
