"""tpu_native backend: the in-process JAX engine as an apiProvider.

The flagship of the rebuild (BASELINE.json north star): where the reference
could only proxy to an external GPU server (reference: src/provider.ts:
210-214), this backend hosts the model itself — HF weights pjit-sharded over
the provider's TPU slice, continuous batching across peers, tokens streamed
back as OpenAI-style chat.completion.chunk SSE lines so existing clients
can't tell the difference (same wire format the proxy backends forward).
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, AsyncIterator

from symmetry_tpu.engine.engine import InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import AsyncSession, Scheduler
from symmetry_tpu.provider.backends.base import (
    BackendError,
    InferenceBackend,
    InferenceRequest,
    StreamChunk,
)
from symmetry_tpu.utils.logging import logger as log

DEFAULT_MAX_NEW_TOKENS = 512


class TpuNativeBackend(InferenceBackend):
    """Two isolation modes (tpu.engine_isolation):

    "process" (default): the engine lives in a host subprocess behind a
    JSON-lines pipe (engine/host.py). Measured necessity, not taste: the
    in-process engine thread's GIL-held device syncs starved the
    provider's event loop so badly that every client's TTFT equalled the
    benchmark's wall time.

    "inproc": the engine thread shares this process (tests, debugging,
    and anything that needs direct engine access).
    """

    name = "tpu_native"

    def __init__(self, config: Any) -> None:
        self._config = config
        self._model_name = config.model_name
        self._engine: InferenceEngine | None = None
        self._scheduler: Scheduler | None = None
        self._command_loop = None
        self._proc: asyncio.subprocess.Process | None = None
        self._cfg_path: str | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        self._reader: asyncio.Task | None = None
        self._started = False
        self._host_dead = False
        self._engine_alive = True  # host-reported scheduler liveness
        self._stats_waiters: list[asyncio.Future] = []
        self._trace_waiters: list[asyncio.Future] = []
        # Measured host-pipe clock offset (host monotonic − provider
        # monotonic), from the startup clock handshake. On Linux both
        # processes read one CLOCK_MONOTONIC so it lands near zero — but
        # it is MEASURED, not assumed: host stamps are reconciled through
        # it instead of clamping negative cross-process spans to zero.
        self._clock_offset: float = 0.0
        # Admission capacity for the provider's overload shedding: the
        # engine serves `slots` streams concurrently; beyond
        # slots + max_queue, new requests would wait more than ~one slot
        # rotation, so the provider rejects them with a busy error.
        tpu = config.tpu
        self.slots = tpu.max_batch_size
        extra = tpu.max_queue if tpu.max_queue is not None else self.slots
        self.queue_limit = self.slots + max(0, extra)
        self.admission_ttft_bound_s = tpu.max_ttft_s
        # Relay-side emit accounting: host frames read vs events carried.
        # frames << events means the batched `events` protocol is doing
        # its job (one pipe read fans out a whole decode block).
        self.relay_stats = {"host_frames": 0, "host_events": 0,
                            "host_batched_frames": 0}
        # Per-stage TTFT attribution (round-4 task #3: the ~2 s
        # engine→provider hop): each first event carries the host's
        # monotonic stage stamps ("t" field), and this side closes the
        # chain with its own submit/receipt stamps. All CLOCK_MONOTONIC —
        # one clock across processes on Linux.
        #   submit   provider stream start → host-pipe submit written
        #   pipe_in  submit written → host read + tokenized + enqueued
        #   queue    enqueued → entered a placement group
        #   prefill  placement pick → first token sampled
        #   emit     first token → host pipe write (block-flush hold)
        #   relay    host pipe write → this process relays the event
        from symmetry_tpu.utils.trace import Histogram

        self.stage_hists = {name: Histogram() for name in
                            ("submit", "pipe_in", "queue", "prefill",
                             "emit", "relay")}

    @property
    def _process_mode(self) -> bool:
        return getattr(self._config.tpu, "engine_isolation",
                       "process") == "process"

    async def start(self) -> None:
        """Load weights and start the engine (may take minutes for large
        checkpoints; nothing here blocks the event loop)."""
        if self._started:
            return
        tpu_cfg = self._config.tpu
        mh = tpu_cfg.multihost
        if mh and mh.get("num_processes", 1) > 1 and mh.get("process_id", 0) != 0:
            # Refuse BEFORE joining the distributed job / loading weights —
            # a wrong-rank provider would become a dead participant the
            # other ranks hang on.
            raise BackendError(
                "only rank 0 runs the provider; start other ranks with "
                "`python -m symmetry_tpu.provider --worker`")
        if self._process_mode:
            await self._start_host_process()
        else:
            await self._start_inproc()
        self._started = True

    async def _start_inproc(self) -> None:
        from symmetry_tpu.utils.compile_cache import enable_compile_cache

        tpu_cfg = self._config.tpu
        mh = tpu_cfg.multihost
        enable_compile_cache(tpu_cfg)

        def build() -> InferenceEngine:
            return InferenceEngine.from_tpu_config(tpu_cfg)

        self._engine = await asyncio.to_thread(build)
        sched_engine = self._engine
        if mh and mh.get("num_processes", 1) > 1:
            # Rank 0 fronts the network; its scheduler drives all ranks in
            # lockstep through the command loop (parallel/multihost.py).
            from symmetry_tpu.parallel.multihost import (
                CommandLoop, MultihostEngine)

            self._command_loop = CommandLoop(self._engine,
                                             is_coordinator=True)
            sched_engine = MultihostEngine(self._command_loop)
        # Compile the decode program before taking traffic: the first
        # request must never stall every stream on a fresh XLA compile.
        await asyncio.to_thread(sched_engine.warmup)
        self._scheduler = Scheduler(sched_engine)
        self._scheduler.start()
        log.info(
            f"tpu_native engine up (inproc): model={self._model_name} "
            f"slots={self._engine.max_slots} seq={self._engine.max_seq_len}")

    async def _start_host_process(self) -> None:
        import sys
        import tempfile

        import yaml

        cfg = {k: v for k, v in self._config.get_all().items()
               if k != "apiKey"}
        with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                         delete=False) as fh:
            yaml.safe_dump(cfg, fh)
            self._cfg_path = fh.name
        self._proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "symmetry_tpu.engine.host", self._cfg_path,
            stdin=asyncio.subprocess.PIPE, stdout=asyncio.subprocess.PIPE,
            # readline() is bounded by the StreamReader limit (64 KiB
            # default) and raises past it — a full-ring {"op":"trace"}
            # reply is a single multi-MB line, which would kill the
            # reader task and wedge every stream. 32 MiB matches the
            # wire-frame bound.
            limit=32 * 1024 * 1024)
        # await the ready line (weight loading + warmup happen in the host)
        while True:
            line = await self._proc.stdout.readline()
            if not line:
                rc = await self._proc.wait()
                raise BackendError(f"engine host died during startup "
                                   f"(rc={rc})")
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if msg.get("op") == "ready":
                break
        await self._clock_handshake()
        self._reader = asyncio.get_running_loop().create_task(
            self._read_events())
        log.info(f"tpu_native engine host up (pid {self._proc.pid}): "
                 f"model={self._model_name} "
                 f"clock_offset={self._clock_offset * 1e6:+.0f}us")

    async def _clock_handshake(self, rounds: int = 5) -> None:
        """Measure the host's monotonic-clock offset before any traffic.

        Each round brackets the host's clock read between two local
        stamps; the min-RTT sample's NTP midpoint wins (utils/trace.
        clock_handshake_offset). Runs before the reader task exists, so
        replies are read directly off the pipe — nothing else can be
        writing yet (no requests submitted, stats only on demand)."""
        from symmetry_tpu.utils.trace import clock_handshake_offset

        samples: list[tuple[float, float, float]] = []
        for _ in range(rounds):
            t0 = time.monotonic()
            await self._host_send({"op": "clock", "t0": t0})
            while True:
                line = await self._proc.stdout.readline()
                if not line:
                    raise BackendError(
                        "engine host died during clock handshake")
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if msg.get("op") == "clock" and msg.get("t0") == t0:
                    samples.append((t0, float(msg["t"]), time.monotonic()))
                    break
        self._clock_offset = clock_handshake_offset(samples)

    async def _read_events(self) -> None:
        assert self._proc is not None and self._proc.stdout is not None
        while True:
            line = await self._proc.stdout.readline()
            if not line:
                break  # host exited
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            op = msg.get("op")
            if op == "stats":
                # stats reply: liveness for the health loop + the full
                # scheduler breakdown for engine_stats() consumers
                self._engine_alive = bool(msg.get("engine_alive", True))
                waiters, self._stats_waiters = self._stats_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == "trace":
                waiters, self._trace_waiters = self._trace_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(msg)
                continue
            if op == "events":
                # Batched frame: one pipe line carries every slot's delta
                # for a decode block. Fan out in frame order — per-request
                # (and cross-request) ordering is the list order.
                events = msg.get("events")
                if not isinstance(events, list):
                    continue
                self.relay_stats["host_frames"] += 1
                self.relay_stats["host_batched_frames"] += 1
                self.relay_stats["host_events"] += len(events)
                for ev in events:
                    if not isinstance(ev, dict):
                        continue
                    q = self._queues.get(str(ev.get("id", "")))
                    if q is not None:
                        q.put_nowait(ev)
                continue
            if op != "event":
                continue
            self.relay_stats["host_frames"] += 1
            self.relay_stats["host_events"] += 1
            q = self._queues.get(str(msg.get("id", "")))
            if q is not None:
                q.put_nowait(msg)
        # fail every open stream — the host is gone. _host_dead also fences
        # NEW streams (they would otherwise register a queue nobody feeds
        # and hang forever).
        self._host_dead = True
        for q in self._queues.values():
            q.put_nowait({"op": "event", "done": True,
                          "finish_reason": "error",
                          "error": "engine host exited", "text": ""})

    async def _host_send(self, obj: dict) -> None:
        assert self._proc is not None and self._proc.stdin is not None
        self._proc.stdin.write(
            (json.dumps(obj, separators=(",", ":")) + "\n").encode())
        await self._proc.stdin.drain()

    async def stop(self) -> None:
        self._started = False
        if self._proc is not None:
            import contextlib
            import os

            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": "shutdown"})
            try:
                await asyncio.wait_for(self._proc.wait(), 30)
            except asyncio.TimeoutError:
                self._proc.kill()
                await self._proc.wait()  # reap — no zombie
            if self._reader is not None:
                self._reader.cancel()
                self._reader = None
            if self._cfg_path:
                with contextlib.suppress(OSError):
                    os.unlink(self._cfg_path)
            self._proc = None
        if self._scheduler is not None:
            await asyncio.to_thread(self._scheduler.stop)
            if self._command_loop is not None:
                self._command_loop.stop()  # release worker ranks
                self._command_loop = None
            self._scheduler = None
            self._engine = None

    async def _probe_host_stats(self, timeout: float = 10.0) -> dict | None:
        """One fresh stats round-trip to the host; None on timeout/failure
        (a fire-and-forget probe would return the PREVIOUS probe's answer,
        delaying wedge detection by a health-loop period)."""
        import contextlib

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._stats_waiters.append(fut)
        try:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": "stats"})
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            if fut in self._stats_waiters:
                self._stats_waiters.remove(fut)

    async def _probe_host_trace(self, timeout: float = 10.0) -> dict | None:
        """One trace-ring round-trip to the host; None on timeout."""
        import contextlib

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._trace_waiters.append(fut)
        try:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send({"op": "trace"})
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            if fut in self._trace_waiters:
                self._trace_waiters.remove(fut)

    async def trace_components(self) -> list[dict]:
        """Host + scheduler span rings, reconciled onto THIS process's
        clock: each component's clock_offset_s gains the measured
        host-pipe offset, so the provider's merge needs no knowledge of
        which process a span came from."""
        if self._proc is not None:
            if self._host_dead or self._proc.returncode is not None:
                return []
            msg = await self._probe_host_trace()
            if msg is None:
                return []
            comps = []
            for comp in msg.get("components") or []:
                if isinstance(comp, dict):
                    comps.append({**comp, "clock_offset_s":
                                  float(comp.get("clock_offset_s", 0.0))
                                  + self._clock_offset})
            return comps
        if self._scheduler is not None:
            trace_export = getattr(self._scheduler, "trace_export", None)
            if trace_export is not None:
                return [trace_export()]  # same process — offset 0
        return []

    async def engine_stats(self) -> dict | None:
        """The scheduler's serving breakdown (counters, engine-side TTFT,
        admission dispatch and block-interval percentiles) — surfaced
        through provider METRICS so a benchmark capture can attribute
        stalls to engine vs relay/wire (round-3 verdict #1/#3)."""
        if self._proc is not None:
            if self._host_dead or self._proc.returncode is not None:
                return None
            msg = await self._probe_host_stats()
            if msg is None:
                return None
            out = {k: v for k, v in msg.items() if k != "op"}
            out["relay"] = dict(self.relay_stats)
            out["clock_offset_s"] = round(self._clock_offset, 6)
            out["stages"] = {name: h.to_dict()
                             for name, h in self.stage_hists.items()
                             if h.count}
            return out
        if self._scheduler is None:
            return None
        stats = getattr(self._scheduler, "stats", None)
        return stats() if stats is not None else dict(self._scheduler.metrics)

    async def healthy(self) -> bool:
        """Engine liveness: a wedged decode loop must fail this (SURVEY §5.3
        — an engine wedge unregisters the provider). In process mode the
        host reports its scheduler thread's liveness through the stats op
        (engine_alive); a dead host or dead engine thread both fail."""
        if self._proc is not None:
            if self._host_dead or self._proc.returncode is not None:
                return False
            if await self._probe_host_stats() is None:
                return False
            return self._engine_alive
        if self._engine is None or self._scheduler is None:
            return False
        thread = self._scheduler._thread
        return thread is not None and thread.is_alive()

    def _chunk_line(self, request_id: str, created: int, delta: dict,
                    finish: str | None = None) -> str:
        payload = {
            "id": request_id,
            "object": "chat.completion.chunk",
            "created": created,
            "model": self._model_name,
            "choices": [{"index": 0, "delta": delta,
                         "finish_reason": finish}],
        }
        return f"data: {json.dumps(payload)}"

    async def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        if not self._started:
            raise BackendError("tpu_native backend not started")
        max_new = (request.max_tokens if request.max_tokens is not None
                   else DEFAULT_MAX_NEW_TOKENS)
        if max_new < 1:
            raise BackendError(f"max_tokens must be >= 1, got {max_new}")
        request_id = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        created = int(time.time())

        if self._proc is not None:
            async for chunk in self._stream_host(request, request_id,
                                                 created, max_new):
                yield chunk
            return

        engine = self._engine
        try:
            prompt_ids = engine.tokenizer.apply_chat_template(request.messages)
        except Exception as exc:  # tokenizer/template failure
            raise BackendError(f"tokenization failed: {exc}") from exc

        session = AsyncSession(self._scheduler,
                               loop=asyncio.get_running_loop())
        session.submit(prompt_ids, SamplingParams.from_request(request),
                       max_new, request_id=request_id,
                       speculative=request.speculative,
                       trace_id=request.trace_id)

        def chunk_line(delta: dict, finish: str | None = None) -> str:
            return self._chunk_line(request_id, created, delta, finish)

        try:
            yield StreamChunk(raw=chunk_line({"role": "assistant"}), text="")
            reported = 0
            async for ev in session.events():
                if ev.error is not None:
                    raise BackendError(ev.error)
                if ev.text:
                    # exact token accounting: tokens_emitted is the
                    # cumulative streamed-token count, a block chunk
                    # carries the delta (EOS and discarded post-finish
                    # tokens never appear in it)
                    n_new = max(ev.tokens_emitted - reported, 0)
                    reported = max(ev.tokens_emitted, reported)
                    yield StreamChunk(raw=chunk_line({"content": ev.text}),
                                      text=ev.text, tokens=n_new)
                if ev.done:
                    yield StreamChunk(
                        raw=chunk_line({}, finish=ev.finish_reason or "stop"),
                        text="")
                    yield StreamChunk(raw="data: [DONE]", text="", done=True)
        finally:
            session.cancel()  # no-op if complete; frees the slot if client left

    def _observe_stages(self, t_recv: float, t_submit: float,
                        t: dict) -> None:
        """Fold one request's first-event stage stamps into the per-stage
        TTFT histograms.

        Host stamps are mapped onto THIS process's clock through the
        measured handshake offset (host − provider) before differencing —
        the old code assumed zero offset and clamped the resulting
        negative cross-process spans to zero, which silently zeroed the
        pipe_in/relay legs whenever clock reads interleaved. Spans are
        recorded as measured: residual sub-RTT jitter may still produce a
        microsecond-negative value, and hiding it would misstate the
        distribution the same way the clamp did."""
        now = time.monotonic()
        off = self._clock_offset
        recv = t["recv"] - off if "recv" in t else t_submit
        picked = t["picked"] - off if "picked" in t else recv
        first = t["first"] - off if "first" in t else picked
        out = t["out"] - off if "out" in t else first
        spans = {"submit": t_submit - t_recv,
                 "pipe_in": recv - t_submit,
                 "queue": picked - recv,
                 "prefill": first - picked,
                 "emit": out - first,
                 "relay": now - out}
        for name, span in spans.items():
            self.stage_hists[name].observe(span)

    async def _stream_host(self, request: InferenceRequest, request_id: str,
                           created: int, max_new: int
                           ) -> AsyncIterator[StreamChunk]:
        """Host-process path: submit over the pipe, relay its events."""
        if self._host_dead:
            raise BackendError("engine host exited")
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = queue
        completed = False
        t_recv = time.monotonic()
        try:
            await self._host_send({
                "op": "submit", "id": request_id,
                "messages": request.messages, "max_new": max_new,
                "sampling": {"temperature": request.temperature or 0.0,
                             "top_p": (request.top_p
                                       if request.top_p is not None else 1.0),
                             "top_k": getattr(request, "top_k", None) or 0,
                             "seed": request.seed},
                **({"speculative": request.speculative}
                   if request.speculative is not None else {}),
                **({"trace": request.trace_id}
                   if request.trace_id else {})})
            t_submit = time.monotonic()
            yield StreamChunk(
                raw=self._chunk_line(request_id, created,
                                     {"role": "assistant"}), text="")
            while True:
                # Generous ceiling: even a deep chunked prefill emits
                # within minutes; a host that is alive-but-wedged would
                # otherwise hang this stream forever (health checks
                # deregister the provider, but open streams must end too).
                try:
                    ev = await asyncio.wait_for(queue.get(), 600)
                except asyncio.TimeoutError:
                    raise BackendError(
                        "engine host produced no event for 600s") from None
                stamps = ev.get("t")
                if isinstance(stamps, dict):
                    self._observe_stages(t_recv, t_submit, stamps)
                err = ev.get("error")
                if err and ev.get("finish_reason") == "error":
                    raise BackendError(err)
                text = ev.get("text", "")
                if text:
                    yield StreamChunk(
                        raw=self._chunk_line(request_id, created,
                                             {"content": text}),
                        text=text, tokens=int(ev.get("tokens_new", 0)))
                if ev.get("done"):
                    completed = True
                    yield StreamChunk(
                        raw=self._chunk_line(
                            request_id, created, {},
                            finish=ev.get("finish_reason") or "stop"),
                        text="")
                    yield StreamChunk(raw="data: [DONE]", text="",
                                      done=True)
                    return
        finally:
            self._queues.pop(request_id, None)
            if (not completed and self._proc is not None
                    and self._proc.returncode is None):
                # client abandoned the stream: free the slot host-side
                import contextlib

                with contextlib.suppress(ConnectionError, OSError):
                    await self._host_send({"op": "cancel", "id": request_id})
