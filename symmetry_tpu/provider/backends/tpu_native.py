"""tpu_native backend: the in-process JAX engine as an apiProvider.

The flagship of the rebuild (BASELINE.json north star): where the reference
could only proxy to an external GPU server (reference: src/provider.ts:
210-214), this backend hosts the model itself — HF weights pjit-sharded over
the provider's TPU slice, continuous batching across peers, tokens streamed
back as OpenAI-style chat.completion.chunk SSE lines so existing clients
can't tell the difference (same wire format the proxy backends forward).
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, AsyncIterator

from symmetry_tpu.engine.engine import EngineError, InferenceEngine, SamplingParams
from symmetry_tpu.engine.scheduler import AsyncSession, Scheduler
from symmetry_tpu.provider.backends.base import (
    BackendError,
    InferenceBackend,
    InferenceRequest,
    StreamChunk,
)
from symmetry_tpu.utils.logging import logger as log

DEFAULT_MAX_NEW_TOKENS = 512


class TpuNativeBackend(InferenceBackend):
    name = "tpu_native"

    def __init__(self, config: Any) -> None:
        self._config = config
        self._model_name = config.model_name
        self._engine: InferenceEngine | None = None
        self._scheduler: Scheduler | None = None
        self._command_loop = None

    async def start(self) -> None:
        """Load weights and start the engine thread (may take minutes for
        large checkpoints; runs in a worker thread to keep the loop live)."""
        if self._engine is not None:
            return
        tpu_cfg = self._config.tpu
        mh = tpu_cfg.multihost
        if mh and mh.get("num_processes", 1) > 1 and mh.get("process_id", 0) != 0:
            # Refuse BEFORE joining the distributed job / loading weights —
            # a wrong-rank provider would become a dead participant the
            # other ranks hang on.
            raise BackendError(
                "only rank 0 runs the provider; start other ranks with "
                "`python -m symmetry_tpu.provider --worker`")

        def build() -> InferenceEngine:
            return InferenceEngine.from_tpu_config(tpu_cfg)

        self._engine = await asyncio.to_thread(build)
        sched_engine = self._engine
        if mh and mh.get("num_processes", 1) > 1:
            # Rank 0 fronts the network; its scheduler drives all ranks in
            # lockstep through the command loop (parallel/multihost.py).
            from symmetry_tpu.parallel.multihost import (
                CommandLoop, MultihostEngine)

            self._command_loop = CommandLoop(self._engine,
                                             is_coordinator=True)
            sched_engine = MultihostEngine(self._command_loop)
        # Compile the decode program before taking traffic: the first
        # request must never stall every stream on a fresh XLA compile.
        await asyncio.to_thread(sched_engine.warmup)
        self._scheduler = Scheduler(sched_engine)
        self._scheduler.start()
        log.info(
            f"tpu_native engine up: model={self._model_name} "
            f"slots={self._engine.max_slots} seq={self._engine.max_seq_len}")

    async def stop(self) -> None:
        if self._scheduler is not None:
            await asyncio.to_thread(self._scheduler.stop)
            if self._command_loop is not None:
                self._command_loop.stop()  # release worker ranks
                self._command_loop = None
            self._scheduler = None
            self._engine = None

    async def healthy(self) -> bool:
        """Engine liveness: a wedged decode loop must fail this (SURVEY §5.3
        — an engine wedge unregisters the provider)."""
        if self._engine is None or self._scheduler is None:
            return False
        thread = self._scheduler._thread
        return thread is not None and thread.is_alive()

    async def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        if self._engine is None or self._scheduler is None:
            raise BackendError("tpu_native backend not started")
        engine = self._engine

        try:
            prompt_ids = engine.tokenizer.apply_chat_template(request.messages)
        except Exception as exc:  # tokenizer/template failure
            raise BackendError(f"tokenization failed: {exc}") from exc

        max_new = (request.max_tokens if request.max_tokens is not None
                   else DEFAULT_MAX_NEW_TOKENS)
        if max_new < 1:
            raise BackendError(f"max_tokens must be >= 1, got {max_new}")
        session = AsyncSession(self._scheduler,
                               loop=asyncio.get_running_loop())
        request_id = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        session.submit(prompt_ids, SamplingParams.from_request(request),
                       max_new, request_id=request_id)
        created = int(time.time())

        def chunk_line(delta: dict, finish: str | None = None) -> str:
            payload = {
                "id": request_id,
                "object": "chat.completion.chunk",
                "created": created,
                "model": self._model_name,
                "choices": [{"index": 0, "delta": delta,
                             "finish_reason": finish}],
            }
            return f"data: {json.dumps(payload)}"

        try:
            yield StreamChunk(raw=chunk_line({"role": "assistant"}), text="")
            async for ev in session.events():
                if ev.error is not None:
                    raise BackendError(ev.error)
                if ev.text:
                    yield StreamChunk(raw=chunk_line({"content": ev.text}),
                                      text=ev.text)
                if ev.done:
                    yield StreamChunk(
                        raw=chunk_line({}, finish=ev.finish_reason or "stop"),
                        text="")
                    yield StreamChunk(raw="data: [DONE]", text="", done=True)
        finally:
            session.cancel()  # no-op if complete; frees the slot if client left
