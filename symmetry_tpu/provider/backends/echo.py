"""Echo backend: deterministic fake model for tests and protocol bring-up.

Streams the last user message back word-by-word as OpenAI-style SSE chunks —
the 'fake echo model' seam SURVEY §4 calls for, letting the full
client→server→provider path run with no TPU and no external server.

It participates in request tracing like a real engine would: each stream
records a backend span (with the request's trace id) into its own bounded
ring and contributes it to the provider's merged Perfetto export — so the
trace pipeline (client → provider → backend components, one reconciled
clock) is exercisable in CI with no TPU and no subprocess.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator

from symmetry_tpu.provider.backends.base import (
    InferenceBackend,
    InferenceRequest,
    StreamChunk,
)
from symmetry_tpu.utils.trace import Tracer


class EchoBackend(InferenceBackend):
    name = "echo"
    # The echo is deterministic, so resumption is exact: the completion
    # IS the last user message, and a resume just skips the word-chunks
    # the client already holds — the protocol-level resume drill with no
    # TPU and no subprocess (chaos smoke, failover tests).
    supports_resume = True

    def __init__(self, delay_s: float = 0.0) -> None:
        self._delay = delay_s
        self.tracer = Tracer()

    async def trace_components(self) -> list[dict]:
        # Same process as the provider — offset 0 by construction.
        return [self.tracer.component("echo")]

    async def stream(self, request: InferenceRequest) -> AsyncIterator[StreamChunk]:
        t0 = time.monotonic()
        last_user = ""
        for m in reversed(request.messages):
            if m.get("role") == "user":
                last_user = m.get("content", "")
                break
        words = last_user.split(" ") or [""]
        # Resume: skip the chunks whose cumulative text the client
        # already received (one word ≈ one token here); yield only the
        # continuation. Skipping is by CHARACTER COUNT, trusting the
        # caller's resume_text to be the prefix it claims — a
        # wrong-content text of the same length yields the canonical
        # completion from that offset, not a splice onto the caller's
        # text (fine for the protocol drill this backend exists for).
        skip_chars = len(request.resume_text or "")
        emitted = 0
        n_words = 0
        for i, word in enumerate(words):
            token = word if i == 0 else " " + word
            if skip_chars >= len(token):
                skip_chars -= len(token)
                n_words += 1
                continue
            if skip_chars:
                # Resume boundary inside a chunk: yield only the unseen
                # tail — the client splices text, so replaying received
                # characters would duplicate them.
                token = token[skip_chars:]
                skip_chars = 0
            chunk = {
                "object": "chat.completion.chunk",
                "model": "echo",
                "choices": [{"index": 0, "delta": {"content": token}}],
            }
            yield StreamChunk(raw=f"data: {json.dumps(chunk)}", text=token,
                              tokens=1)
            emitted += 1
            if self._delay:
                await asyncio.sleep(self._delay)
        wall = time.monotonic() - t0
        self.tracer.record("echo_stream", t0, wall,
                           trace_id=request.trace_id, tokens=emitted,
                           resumed_from=n_words)
        # Minimal symledger costs block (source "estimated": no device
        # behind this backend — the stream wall stands in for decode
        # time) so the fleet wiring (costs on the final frame, provider
        # sym_request_* fold, goodput window) is exercisable without an
        # engine. Shape-compatible with engine/ledger.py costs().
        costs = {
            "device_s": {"decode": round(wall, 6)},
            "device_total_s": round(wall, 6),
            "queue_s": 0.0,
            "emit_s": 0.0,
            "wasted_s": {},
            "wasted_total_s": 0.0,
            "tokens": emitted,
            "source": "estimated",
            "finish": "stop",
        }
        yield StreamChunk(raw="data: [DONE]", text="", done=True,
                          costs=costs)
