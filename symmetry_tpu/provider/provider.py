"""The provider node: the heart of the framework.

Re-creation of the reference's `SymmetryProvider` lifecycle
(src/provider.ts:21-323) — swarm presence, server registration with challenge
auth, per-peer inference streaming with backpressure, data collection — with
the deliberate upgrades SURVEY §§3-5 call for:

  - enforced mutual auth (reference's server verification is advisory,
    src/provider.ts:157-171)
  - session tokens verified offline against the trusted serverKey
  - accurate connection accounting reported to the server (the reference's
    `_providerConnections` counter is decremented but never incremented —
    latent bug, src/provider.ts:76-80)
  - reconnect-with-backoff to the server; the reference never reconnects
  - graceful drain on shutdown + explicit `leave` (the reference defines the
    key but never sends it, src/constants.ts:11)
  - backend health checks: a wedged engine deregisters the provider
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
from collections import deque
from typing import Any

from symmetry_tpu.identity import Identity
from symmetry_tpu.network.peer import Peer
from symmetry_tpu.protocol.keys import MessageKey
from symmetry_tpu.provider.backends.base import (
    BackendDeadlineError,
    BackendError,
    BackendRestartingError,
    InferenceBackend,
    InferenceRequest,
    get_backend,
)
from symmetry_tpu.provider.collect import DataCollector
from symmetry_tpu.provider.config import ConfigManager
from symmetry_tpu.server import tokens as session_tokens
from symmetry_tpu.transport.base import Connection, Listener, Transport
from symmetry_tpu.utils.faults import FAULTS, InjectedFault
from symmetry_tpu.utils.logging import log_context, logger
from symmetry_tpu.utils.metrics import (
    METRICS,
    MetricName,
    MetricsServer,
    SloMonitor,
    render_prometheus,
)
from symmetry_tpu.utils.trace import FlightRecorder, Tracer

RECONNECT_BASE_S = 1.0
RECONNECT_MAX_S = 60.0
HEALTH_INTERVAL_S = 15.0


def _load_or_create_secret(path: str) -> bytes:
    """Per-node secret salting the name-derived identity seed.

    Keeps the reference's UX (stable identity from the configured name,
    src/provider.ts:41-43) without its guessable-identity flaw.
    """
    path = os.path.expanduser(path)
    if os.path.exists(path):
        with open(path, "rb") as fh:
            return fh.read()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    secret = os.urandom(32)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    with os.fdopen(fd, "wb") as fh:
        fh.write(secret)
    return secret


class SymmetryProvider:
    def __init__(
        self,
        config: ConfigManager | str | None = None,
        *,
        transport: Transport | None = None,
        identity: Identity | None = None,
        backend: InferenceBackend | None = None,
        server_address: str | None = None,
    ) -> None:
        if isinstance(config, ConfigManager):
            self.config = config
        else:
            self.config = ConfigManager(config_path=config)
        if transport is None:
            from symmetry_tpu.transport import transport_for

            # Scheme-select from the server address — constructor override
            # first, then config (udp:// engages the native udpstream
            # transport; default tcp).
            transport = transport_for(
                server_address or self.config.get("serverAddress") or "")
        self._transport = transport
        if identity is None:
            seed_hex = self.config.get("privateSeed")
            if seed_hex:
                identity = Identity.from_seed(bytes.fromhex(seed_hex))
            else:
                secret_path = self.config.get(
                    "secretPath",
                    os.path.join(self.config.get("path", "~/.config/symmetry"),
                                 "identity.secret"),
                )
                identity = Identity.from_name(
                    self.config.name, _load_or_create_secret(secret_path)
                )
        self.identity = identity
        self.backend = backend if backend is not None else get_backend(self.config)
        self.collector = DataCollector(
            self.config.get("path", "~/.config/symmetry"),
            self.config.data_collection_enabled,
        )
        self._server_address = server_address or self.config.get("serverAddress")
        self._listener: Listener | None = None
        self._server_peer: Peer | None = None
        self._dht: Any = None  # network/dht.py DHTNode when dht: configured
        self._client_peers: set[Peer] = set()
        self._conversation_index: dict[str, int] = {}
        # multiplexed inference: (peer, requestId) -> pump task, so an
        # inferenceCancel can abort exactly one stream
        self._inference_tasks: dict[tuple[int, str], asyncio.Task] = {}
        self._tasks: set[asyncio.Task] = set()
        self._draining = False
        self._in_flight = 0
        self._stopped = asyncio.Event()
        self._server_ready = asyncio.Event()
        # Metrics (SURVEY §5.5: tok/s, queue depth first-class). Latency
        # distributions live in this provider's Tracer (utils/trace.py):
        # spans feed the same log-bucketed histograms stats() reads, so
        # there is exactly one aggregation path — p50/p99 TTFT is the
        # BASELINE.json headline metric.
        self.tracer = Tracer()
        self.metrics: dict[str, Any] = {
            "requests": 0, "tokens_out": 0, "errors": 0, "shed": 0,
        }
        self._last_load_report = -1e9  # throttles shed-triggered METRICS
        # Emit-path wire accounting: closed peers fold their transport
        # write counters in here; stats() adds the live peers on top, so
        # the totals survive disconnects (WriteCork, transport/base.py).
        self._wire_totals = {"writes": 0, "frames": 0,
                             "coalesced_frames": 0, "bytes": 0}
        # TTFT-bounded admission state: requests accepted but not yet
        # streaming, and recent first-token completion stamps (the
        # admission-rate signal the wait estimate divides by).
        self._unstarted = 0
        self._first_token_stamps: deque[float] = deque(maxlen=512)
        self._started_at = time.monotonic()
        # Always-on flight recorder (utils/trace.py): the span rings are
        # already recording; this owns the trigger — SLO breach, backend
        # error, or SIGUSR2 dumps the merged last-window timeline + a
        # stats snapshot to one JSON file, so the LAST bad request is
        # debuggable after the fact. Config (all optional):
        #   flightRecorder: {enabled, dir, windowS, minIntervalS, sloE2eS}
        fr_cfg = self.config.get("flightRecorder") or {}
        self.flight: FlightRecorder | None = None
        if fr_cfg.get("enabled", True):
            slo = fr_cfg.get("sloE2eS")
            self.flight = FlightRecorder(
                fr_cfg.get("dir") or os.path.join(
                    self.config.get("path", "~/.config/symmetry"),
                    "flight"),
                window_s=float(fr_cfg.get("windowS", 30.0)),
                min_interval_s=float(fr_cfg.get("minIntervalS", 30.0)),
                # Coerced at construction like its siblings: a quoted
                # YAML value must fail/convert HERE, not as a TypeError
                # in the per-request SLO comparison.
                slo_e2e_s=float(slo) if slo is not None else None)
        # Fault injection (utils/faults.py): a `faults:` mapping in
        # provider.yaml arms seams in THIS process (the host subprocess
        # loads the same mapping from its config copy; SYMMETRY_FAULTS
        # env reaches both at import). No-op when absent.
        FAULTS.load(self.config.get("faults"))
        # ---- always-on fleet telemetry (utils/metrics.py) ------------
        # The registry families this provider emits. Registered HERE so
        # the exposition endpoint shows every family from the first
        # scrape (an empty counter is a statement; a missing one is a
        # question). `metrics:` config block:
        #   metrics: {enabled: true, port: 9100, host: "127.0.0.1"}
        # port absent/None → no HTTP endpoint (the peer-wire metrics
        # reply still carries the snapshots); port 0 → ephemeral.
        m_cfg = self.config.get("metrics") or {}
        METRICS.enabled = bool(m_cfg.get("enabled", True))
        self._metrics_cfg = m_cfg
        self.metrics_server: MetricsServer | None = None
        self._m_requests = METRICS.counter(
            MetricName.PROVIDER_REQUESTS, "inference requests accepted")
        self._m_tokens_out = METRICS.counter(
            MetricName.PROVIDER_TOKENS_OUT, "tokens streamed to clients")
        self._m_errors = METRICS.counter(
            MetricName.PROVIDER_ERRORS, "inference requests failed")
        self._m_sheds = METRICS.counter(
            MetricName.PROVIDER_SHEDS,
            "requests shed before service", labels=("reason",))
        self._m_in_flight = METRICS.gauge(
            MetricName.PROVIDER_IN_FLIGHT, "requests currently in flight")
        self._m_pending_first = METRICS.gauge(
            MetricName.PROVIDER_PENDING_FIRST_TOKEN,
            "accepted requests not yet streaming")
        self._m_connections = METRICS.gauge(
            MetricName.PROVIDER_CONNECTIONS, "connected client peers")
        self._m_uptime = METRICS.gauge(
            MetricName.PROVIDER_UPTIME, "seconds since provider start")
        self._m_ttft = METRICS.histogram(
            MetricName.PROVIDER_TTFT, "time to first streamed token")
        self._m_e2e = METRICS.histogram(
            MetricName.PROVIDER_E2E, "end-to-end request latency")
        self._m_inter_chunk = METRICS.histogram(
            MetricName.PROVIDER_INTER_CHUNK,
            "gap between consecutive streamed chunks")
        self._m_backend_restarts = METRICS.counter(
            MetricName.PROVIDER_BACKEND_RESTARTS,
            "engine-host deaths handled by the supervisor")
        self._m_flight_dumps = METRICS.counter(
            MetricName.PROVIDER_FLIGHT_DUMPS,
            "flight-recorder dumps written", labels=("reason",))
        # On-demand device profiler (utils/devprof.py, HostOp.PROFILE):
        # a bounded jax.profiler capture on the serving engine,
        # triggered by the `profileCapture` wire op, SIGUSR1, or — when
        # profiler.onSloBreach is set — the SLO burn hook beside the
        # flight recorder. Config (all optional):
        #   profiler: {dir, durationS, onSloBreach}
        self._profiler_cfg = self.config.get("profiler") or {}
        self._profile_running = False
        self._m_profile_captures = METRICS.counter(
            MetricName.PROFILE_CAPTURES,
            "on-demand device profile captures", labels=("reason",))
        # Stream resumption: resumes served (accepted/refused) and the
        # recovery-latency headline — interruption to first CONTINUATION
        # token (the resume request's TTFT as this provider saw it).
        self._m_resumes = METRICS.counter(
            MetricName.PROVIDER_RESUMES,
            "resume requests handled", labels=("outcome",))
        self._m_resume_ttft = METRICS.histogram(
            MetricName.RESUME_TTFT,
            "time to first continuation token of a resume request")
        # symledger fold (`tpu.ledger` knob, on by default): engine
        # backends stamp a per-request cost block on their terminal
        # stream chunk; this side judges SLO attainment for the request
        # (EVERY configured slo: target met — ttft, e2e, worst
        # inter-chunk gap; no targets configured ⇒ trivially attained),
        # exports the per-request attribution families, and maintains
        # the goodput headline: SLO-attaining tokens per attributed
        # device second over the last `maxlen` finished requests. With
        # the knob off no cost blocks arrive and the fold is one dead
        # branch per request.
        self._ledger_on = bool(getattr(
            getattr(self.config, "tpu", None), "ledger", True))
        self._m_req_device_s = METRICS.histogram(
            MetricName.REQUEST_DEVICE_SECONDS,
            "attributed device seconds per finished request",
            labels=("phase",))
        self._m_req_wasted_s = METRICS.counter(
            MetricName.REQUEST_WASTED_SECONDS,
            "device seconds spent on work no client kept",
            labels=("reason",))
        self._m_goodput = METRICS.gauge(
            MetricName.GOODPUT_TOKENS_PER_DEVICE_S,
            "windowed SLO-attaining tokens per attributed device second")
        # (tokens, device_s, attained) per finished request — the
        # goodput gauge's window; the cost ring is the flight
        # recorder's per-request attribution tail.
        self._goodput_window: deque[tuple[int, float, bool]] = deque(
            maxlen=256)
        self._cost_ring: deque[dict] = deque(maxlen=64)
        # SLO burn-rate monitor (`slo:` config block, utils/metrics.py):
        # continuous evaluation over the request stream; a budget burn
        # triggers the flight recorder + a structured log event — SLO
        # breach as a first-class signal, not a bench-time observation.
        self.slo = SloMonitor(self.config.get("slo"),
                              on_breach=self._on_slo_breach)
        if hasattr(self.backend, "attach_slo_monitor"):
            # Live placement input (ROADMAP item 4 remainder): the
            # tpu_native pool heartbeat feeds this monitor's fast-window
            # burn rate into PoolRouter.update_gauges, so placement's
            # burn tie-break runs on the real request stream instead of
            # only queue depth.
            self.backend.attach_slo_monitor(self.slo)

    # ----- lifecycle (reference: init(), src/provider.ts:37-81) -----

    @property
    def address(self) -> str:
        assert self._listener is not None, "provider not started"
        return self._listener.address

    async def start(self, listen_address: str | None = None) -> None:
        await self.backend.start()
        if hasattr(self.backend, "on_host_restart"):
            # Supervised engine host (tpu_native process mode): every
            # crash/wedge the supervisor handles dumps the flight
            # recorder FIRST — the restart must not erase the evidence.
            self.backend.on_host_restart = self._on_backend_restart
        listen_address = listen_address or (
            f"{self._transport.scheme}://"
            f"{self.config.get('listenHost', '0.0.0.0')}"
            f":{self.config.get('listenPort', 0)}"
        )
        self._listener = await self._transport.listen(listen_address, self._on_peer)
        logger.info(
            f"provider {self.config.name!r} listening on {self.address} "
            f"key={self.identity.public_hex} model={self.config.model_name!r}"
        )
        if self.config.public:
            self._spawn(self._server_loop())
        self._spawn(self._health_loop())
        await self._join_dht()
        self._start_puncher()
        self._install_sigusr2()
        self._install_sigusr1()
        self._start_metrics_server()

    def _start_metrics_server(self) -> None:
        """Prometheus exposition endpoint (`metrics.port`): a stdlib
        http.server thread serving GET /metrics with this process's
        registry merged with the engine host(s)' tier-labeled
        snapshots. Best-effort: a bound-port failure must not take down
        an otherwise healthy provider."""
        port = self._metrics_cfg.get("port")
        if port is None or not METRICS.enabled:
            return
        loop = asyncio.get_running_loop()

        def render() -> str:
            # Scrape threads bridge into the event loop: the engine
            # host probe is async (pipe round-trip), and the loop owns
            # every waiter list.
            fut = asyncio.run_coroutine_threadsafe(
                self._metrics_exposition(), loop)
            return fut.result(timeout=10.0)

        try:
            server = MetricsServer(
                render, host=self._metrics_cfg.get("host", "127.0.0.1"),
                port=int(port))
            server.start()
        except OSError as exc:
            logger.error(f"metrics endpoint disabled: {exc}")
            return
        self.metrics_server = server
        logger.info(f"metrics: http://"
                    f"{self._metrics_cfg.get('host', '127.0.0.1')}:"
                    f"{server.port}/metrics")

    async def metrics_snapshots(self) -> list[dict]:
        """This process's registry snapshot plus the backend's
        tier-labeled engine-host snapshots — the payload of the
        peer-wire metrics reply and the HTTP exposition alike."""
        self._m_uptime.set(round(time.monotonic() - self._started_at, 1))
        snaps = [{"snapshot": METRICS.snapshot(compact=True),
                  "labels": {}}]
        fn = getattr(self.backend, "metrics_snapshots", None)
        if fn is not None:
            try:
                snaps.extend(await fn() or [])
            except Exception as exc:  # noqa: BLE001 — scrape is diagnostics
                logger.warning(f"backend metrics snapshot failed: {exc}")
        return snaps

    async def _metrics_exposition(self) -> str:
        return render_prometheus(await self.metrics_snapshots())

    def _on_slo_breach(self, event: dict) -> None:
        """SLO budget burn: one structured log event (JSON mode carries
        component="slo", t_mono, and the ambient trace_id of the
        request that tipped the budget) plus a flight-recorder dump —
        the window that contains the burn, captured while it is still
        in the rings."""
        with log_context(component="slo"):
            logger.error(
                f"SLO burn: {event['slo']} target "
                f"{event['target_s']}s objective {event['objective']} — "
                f"burn fast {event['burn_fast']}x / slow "
                f"{event['burn_slow']}x over threshold "
                f"{event['burn_threshold']}x "
                f"({event['samples_fast']} samples in "
                f"{event['fast_window_s']:.0f}s)")
        if self.flight is not None:
            self._spawn(self._flight_dump(f"slo_burn_{event['slo']}",
                                          force=True))
        if self._profiler_cfg.get("onSloBreach"):
            # Opt-in: a capture serializes sampled dispatches for its
            # whole window, so burning error budget has to be judged
            # worth the heavier evidence explicitly. The flight dump
            # above shows WHAT burned; this shows what the DEVICE was
            # doing while it burned.
            self._spawn(self._capture_profile(
                f"slo_burn_{event['slo']}"))

    def _install_sigusr2(self) -> None:
        """SIGUSR2 → flight-recorder dump (operator-triggered capture of
        the last N seconds, no restart, no client needed). Best-effort:
        unavailable off the main thread and on non-Unix loops."""
        self._sigusr2_installed = False
        if self.flight is None:
            return
        import signal

        try:
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGUSR2,
                lambda: self._spawn(self._flight_dump("sigusr2",
                                                      force=True)))
            self._sigusr2_installed = True
        except (NotImplementedError, ValueError, RuntimeError):
            logger.debug("SIGUSR2 flight-recorder trigger unavailable "
                         "on this platform/thread")

    async def _capture_profile(self, reason: str,
                               duration_s: float | None = None) -> dict:
        """Run one on-demand device profile capture through the backend
        (HostOp.PROFILE underneath). Single-flight: a capture already
        in progress returns a structured error instead of queueing —
        jax.profiler refuses concurrent traces, and stacking windows
        behind an operator's trigger would measure the wrong moment."""
        fn = getattr(self.backend, "capture_profile", None)
        if fn is None:
            return {"error": "backend has no device profiler"}
        if self._profile_running:
            return {"error": "a profile capture is already running"}
        self._profile_running = True
        try:
            out = await fn(
                duration_s=float(
                    duration_s if duration_s is not None
                    else self._profiler_cfg.get("durationS", 2.0)),
                out_dir=self._profiler_cfg.get("dir"))
        except Exception as exc:  # noqa: BLE001 — diagnostics only
            out = {"error": str(exc)}
        finally:
            self._profile_running = False
        if out.get("path"):
            self._m_profile_captures.inc(reason=reason)
            logger.warning(f"device profile ({reason}) → {out['path']}")
        else:
            logger.warning(f"device profile ({reason}) failed: "
                           f"{out.get('error')}")
        return out

    def _install_sigusr1(self) -> None:
        """SIGUSR1 → on-demand device profile capture (the operator's
        'what is the chip doing RIGHT NOW' trigger, the jax.profiler
        analog of SIGUSR2's flight dump). Best-effort like SIGUSR2."""
        self._sigusr1_installed = False
        if getattr(self.backend, "capture_profile", None) is None:
            return
        import signal

        try:
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGUSR1,
                lambda: self._spawn(self._capture_profile("sigusr1")))
            self._sigusr1_installed = True
        except (NotImplementedError, ValueError, RuntimeError):
            logger.debug("SIGUSR1 profile-capture trigger unavailable "
                         "on this platform/thread")

    def _on_backend_restart(self, reason: str) -> None:
        """Backend supervisor hook: an engine-host death/wedge is being
        handled. Leave the debuggable artifact (forced flight dump — the
        window still holds the death) and say so loudly."""
        logger.error(f"engine host {reason}; supervisor restarting it")
        self._m_backend_restarts.inc()
        if self.flight is not None:
            self._spawn(self._flight_dump(f"host_{reason}", force=True))

    def _start_puncher(self) -> None:
        """NAT hole punching (network/natpunch.py): keep this provider
        registered at a rendezvous and answer punch invites, so clients
        behind NATs can reach the UDP listener directly. Requires the
        native udp transport (the raw side channel rides its socket)."""
        self._puncher = None
        punch_cfg = self.config.get("natPunch")
        if not punch_cfg:
            return
        raw_factory = getattr(self._listener, "raw_channel", None)
        if raw_factory is None:
            logger.warning("natPunch configured but the transport has no "
                           "raw channel (udp:// required); punching disabled")
            return
        from symmetry_tpu.network.dht import parse_host_port
        from symmetry_tpu.network.natpunch import ProviderPuncher

        try:
            rdv = parse_host_port(punch_cfg["rendezvous"])
        except (KeyError, ValueError) as exc:
            logger.error(f"natPunch disabled: {exc}")
            return
        self._puncher = ProviderPuncher(raw_factory(), rdv, self.identity)
        self._puncher.start()

    async def _join_dht(self) -> None:
        """Announce on the Kademlia DHT (network/dht.py) so clients can
        discover this provider WITHOUT the central server — the reference's
        hyperswarm topic-announce (src/provider.ts:44-48), decentralized
        leg. Topic = discovery_key(our public key)."""
        dht_cfg = self.config.get("dht")
        if not dht_cfg:
            return
        from symmetry_tpu.network.dht import DHTNode, parse_host_port

        # Discovery is an add-on: NO failure here (bad config, occupied
        # UDP port, unreachable bootstrap) may take down an otherwise
        # healthy provider.
        try:
            bootstrap = [parse_host_port(e)
                         for e in dht_cfg.get("bootstrap", [])]
            # The identity signs announce records: DHT nodes verify them
            # against our publicKey, so nobody can shadow or evict this
            # provider's discovery record (network/dht.py).
            self._dht = DHTNode(identity=self.identity)
            await self._dht.start(dht_cfg.get("host", "0.0.0.0"),
                                  int(dht_cfg.get("port", 0)),
                                  bootstrap=bootstrap)
            stored = await self._dht.announce(self.identity.discovery_key, {
                "address": self.address,
                "publicKey": self.identity.public_hex,
                "modelName": self.config.model_name,
            })
        except (ValueError, TypeError, OSError) as exc:
            logger.error(f"dht disabled: {exc}")
            if self._dht is not None:
                await self._dht.stop()
                self._dht = None
            return
        logger.info(f"dht: announced on {stored} node(s) "
                    f"(topic {self.identity.discovery_key.hex()[:12]}…)")

    async def wait_registered(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._server_ready.wait(), timeout)

    async def stop(self, drain_timeout_s: float = 30.0) -> None:
        """Graceful drain: stop accepting, finish in-flight, leave, close."""
        self._draining = True
        if self.metrics_server is not None:
            # First: a scrape against a draining provider should fail
            # fast, not hold the drain window open.
            await asyncio.to_thread(self.metrics_server.stop)
            self.metrics_server = None
        if getattr(self, "_sigusr2_installed", False):
            import signal

            with contextlib.suppress(Exception):
                asyncio.get_running_loop().remove_signal_handler(
                    signal.SIGUSR2)
            self._sigusr2_installed = False
        if getattr(self, "_puncher", None) is not None:
            await self._puncher.stop()
            self._puncher = None
        if self._dht is not None:
            with contextlib.suppress(Exception):
                await self._dht.unannounce(self.identity.discovery_key)
            await self._dht.stop()
            self._dht = None
        deadline = time.monotonic() + drain_timeout_s
        while self._in_flight > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        if self._server_peer is not None and not self._server_peer.closed:
            with contextlib.suppress(ConnectionError, OSError):
                await self._server_peer.send(MessageKey.LEAVE)
            await self._server_peer.close()
        self._stopped.set()
        for task in list(self._tasks):
            task.cancel()
        for peer in list(self._client_peers):
            await peer.close()
        if self._listener is not None:
            await self._listener.close()
        await self.backend.stop()

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    # ----- server registration (reference: joinServer(), src/provider.ts:83-131) -----

    async def _server_loop(self) -> None:
        """Maintain the server connection with exponential backoff."""
        backoff = RECONNECT_BASE_S
        while not self._stopped.is_set() and not self._draining:
            try:
                await self._join_server()
                backoff = RECONNECT_BASE_S  # reset after a successful session
            except asyncio.CancelledError:
                return
            except Exception as exc:
                if not (self._draining or self._stopped.is_set()):
                    logger.warning(f"server connection lost: {exc}")
            self._server_ready.clear()
            if self._stopped.is_set() or self._draining:
                return
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, RECONNECT_MAX_S)

    async def _join_server(self) -> None:
        if not self._server_address:
            raise RuntimeError("public provider requires serverAddress in config")
        conn = await self._transport.dial(self._server_address)
        # The handshake pins the serverKey from config — a MITM or imposter
        # server fails here and we disconnect (not advisory).
        peer = await Peer.connect(
            conn, self.identity, initiator=True,
            expected_remote_key=self.config.server_key,
        )
        self._server_peer = peer
        # Wire-parity challenge flow on top (reference src/provider.ts:95-101).
        challenge = os.urandom(32)
        await peer.send(MessageKey.CHALLENGE, {"challenge": challenge.hex()})
        await peer.send(
            MessageKey.JOIN,
            {
                # Sanitized config — never the apiKey (the reference leaks it,
                # src/provider.ts:103-108).
                "config": self.config.public_view(),
                "discoveryKey": self.identity.discovery_key.hex(),
                "address": self.address,
                "modelName": self.config.model_name,
            },
        )
        async for msg in peer:
            if msg.key == MessageKey.CHALLENGE_RESPONSE:
                sig = bytes.fromhex((msg.data or {}).get("signature", ""))
                if not Identity.verify(challenge, sig, self.config.server_key):
                    await peer.close()
                    raise ConnectionError("server failed challenge verification")
                logger.debug("server signature verified")
            elif msg.key == MessageKey.JOIN_ACK:
                logger.info("registered with server ✅")
                self._server_ready.set()
            elif msg.key == MessageKey.PING:
                await peer.send(MessageKey.PONG)
            elif msg.key == MessageKey.RELAY_OPEN:
                # NAT fallback (network/relay.py): a client that cannot
                # reach us directly asked the server to splice. Dial the
                # server back on a fresh connection and serve the client
                # through it — end-to-end encrypted, server sees only
                # ciphertext.
                relay_id = str((msg.data or {}).get("id", ""))
                if relay_id:
                    self._spawn(self._serve_relay(relay_id))
            else:
                logger.debug(f"provider: unhandled server key {msg.key!r}")
        raise ConnectionError("server closed connection")

    async def _serve_relay(self, relay_id: str) -> None:
        from symmetry_tpu.network.relay import RelayedConnection, await_ready

        try:
            conn = await self._transport.dial(self._server_address)
            peer = await Peer.connect(
                conn, self.identity, initiator=True,
                expected_remote_key=self.config.server_key)
            await peer.send(MessageKey.RELAY_ACCEPT, {"id": relay_id})
            await await_ready(peer, relay_id)
        except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
            logger.warning(f"relay {relay_id[:8]} setup failed: {exc}")
            return
        # From here the relayed channel is an ordinary inbound connection:
        # the client's Noise handshake (with OUR key pinned) runs through
        # it, maxConnections and session checks included.
        await self._on_peer(RelayedConnection(peer, relay_id))

    async def _report_connections(self) -> None:
        if self._server_peer is not None and not self._server_peer.closed:
            with contextlib.suppress(ConnectionError, OSError):
                await self._server_peer.send(
                    MessageKey.CONNECTION_SIZE, len(self._client_peers)
                )

    def _wire_stats(self) -> dict[str, int]:
        """Aggregate per-peer transport write counters: folded totals of
        closed peers + a live read of every open one."""
        out = dict(self._wire_totals)
        for peer in self._client_peers:
            ws = peer.write_stats
            if ws:
                for k in out:
                    out[k] += ws.get(k, 0)
        return out

    def _goodput_stats(self) -> dict[str, Any] | None:
        """Windowed goodput snapshot from the per-request cost folds:
        SLO-attaining tokens over attributed device seconds. None until
        the first cost block arrives (ledger off / nothing finished)."""
        if not self._goodput_window:
            return None
        window = list(self._goodput_window)
        good = sum(t for t, _d, a in window if a)
        total = sum(t for t, _d, _a in window)
        dev_s = sum(d for _t, d, _a in window)
        return {
            "window_requests": len(window),
            "attained_requests": sum(1 for _t, _d, a in window if a),
            "attained_tokens": good,
            "tokens": total,
            "device_s": round(dev_s, 6),
            **({"tokens_per_device_s": round(good / dev_s, 3)}
               if dev_s > 0 else {}),
        }

    def stats(self) -> dict[str, Any]:
        """Serving metrics snapshot: counters, tok/s, TTFT/e2e percentiles."""
        uptime = max(time.monotonic() - self._started_at, 1e-9)
        goodput = self._goodput_stats()
        slots = getattr(self.backend, "slots", None)
        return {
            "requests": self.metrics["requests"],
            "tokens_out": self.metrics["tokens_out"],
            "errors": self.metrics["errors"],
            "shed": self.metrics["shed"],
            "in_flight": self._in_flight,
            # Requests waiting beyond the engine's concurrent slots — the
            # router's steering signal (registry.select_provider prefers
            # providers with the smallest reported backlog).
            "queued": (max(0, self._in_flight - slots)
                       if slots is not None else 0),
            "pending_first_token": self._unstarted,
            **({"queue_limit": self.backend.queue_limit}
               if getattr(self.backend, "queue_limit", None) is not None
               else {}),
            "connections": len(self._client_peers),
            # Corked-wire emit path: writes < frames means same-tick
            # coalescing is collapsing the per-stream fan-out of batched
            # engine blocks into fewer syscalls (transport/base.WriteCork).
            "wire": self._wire_stats(),
            "uptime_s": round(uptime, 1),
            "tok_s": round(self.metrics["tokens_out"] / uptime, 2),
            "ttft_s": self.tracer.histogram("ttft_s").to_dict(),
            "e2e_s": self.tracer.histogram("inference_s").to_dict(),
            # symledger headline: windowed SLO-goodput from the
            # per-request cost folds (absent until one arrives).
            **({"goodput": goodput} if goodput is not None else {}),
            # False when recent DHT announce rounds were fully rejected
            # (clock skew → silently undiscoverable; network/dht.py).
            **({"dht_discoverable": self._dht.is_discoverable}
               if self._dht is not None else {}),
            # Chaos-drill accounting: which armed fault seams fired in
            # this process (absent when no faults are configured).
            **({"faults": FAULTS.counters()} if FAULTS.enabled else {}),
        }

    async def gather_trace(self) -> dict[str, Any]:
        """Merged span-ring snapshot: this provider's tracer plus every
        component the backend contributes (tpu_native: host + scheduler,
        already reconciled onto this process's clock through the measured
        pipe offset). The `trace` wire op's reply payload; also what the
        flight recorder dumps."""
        comps = [self.tracer.component("provider")]
        fn = getattr(self.backend, "trace_components", None)
        if fn is not None:
            try:
                comps.extend(await fn() or [])
            except Exception as exc:  # noqa: BLE001 — diagnostics only
                logger.warning(f"backend trace snapshot failed: {exc}")
        return {"components": comps, "clock": time.monotonic()}

    async def _flight_dump(self, reason: str,
                           force: bool = False) -> str | None:
        """Trigger one flight-recorder dump (rate-limited unless forced)."""
        if self.flight is None:
            return None
        if not force and not self.flight.should_dump():
            return None
        payload = await self.gather_trace()
        stats = self.stats()
        engine_stats = getattr(self.backend, "engine_stats", None)
        if engine_stats is not None:
            with contextlib.suppress(Exception):
                stats["engine"] = await engine_stats()
        if self._cost_ring:
            # symledger tail: the last requests' attributed cost blocks
            # — the dump answers "what was the device doing" per
            # request, not just in aggregate.
            stats["ledger_tail"] = list(self._cost_ring)
        try:
            path = self.flight.dump(reason, payload["components"],
                                    stats=stats)
        except OSError as exc:
            logger.error(f"flight recorder write failed: {exc}")
            return None
        self._m_flight_dumps.inc(reason=reason)
        logger.warning(f"flight recorder: {reason} → {path}")
        return path

    async def _health_loop(self) -> None:
        """Backend health → presence (SURVEY §5.3: engine wedge must
        unregister the provider); piggybacks the load-metrics report the
        protocol reserves the `metrics` key for."""
        while not self._stopped.is_set():
            await asyncio.sleep(HEALTH_INTERVAL_S)
            try:
                ok = await self.backend.healthy()
            except Exception:
                ok = False
            if self._server_peer is not None and not self._server_peer.closed:
                if not ok:
                    logger.error("backend unhealthy; leaving server")
                    with contextlib.suppress(ConnectionError, OSError):
                        await self._server_peer.send(MessageKey.LEAVE)
                else:
                    with contextlib.suppress(ConnectionError, OSError):
                        await self._server_peer.send(MessageKey.METRICS,
                                                     self.stats())

    # ----- client peers (reference: listeners(), src/provider.ts:173-193) -----

    async def _refuse_peer(self, conn: Connection, reason: str,
                           draining: bool = False) -> None:
        """Refuse a new connection LOUDLY: complete the handshake, send a
        structured shed, close. The old silent close left the dialer
        hanging in its Noise handshake until some timeout — a refusing
        provider must cost a client milliseconds, not a timeout, before
        it fails over. `draining` marks the shed terminal for THIS
        provider (shutting down — never coming back), vs a busy/capacity
        shed that a backoff retry may legitimately revisit."""
        self.metrics["shed"] += 1
        self._m_sheds.inc(
            reason="draining" if draining else "connection_limit")
        try:
            # Short handshake hold on purpose: the refusal path runs
            # exactly when the provider is saturated (or leaving), and a
            # slow/hostile dialer must not pin refused connections open —
            # the handshake work per refusal is the price of a structured
            # shed, the hold time doesn't have to be.
            peer = await asyncio.wait_for(
                Peer.connect(conn, self.identity, initiator=False), 2.0)
            await peer.send(MessageKey.INFERENCE_ERROR,
                            {"error": reason, "busy": True,
                             **({"draining": True} if draining else {})})
            await peer.close()
        except (ConnectionError, OSError, asyncio.TimeoutError):
            with contextlib.suppress(Exception):
                await conn.close()

    async def _on_peer(self, conn: Connection) -> None:
        if self._draining:
            await self._refuse_peer(conn, "provider draining",
                                    draining=True)
            return
        if len(self._client_peers) >= self.config.max_connections:
            # maxConnections cap (src/provider.ts:38-40) — refused with
            # the same structured shed as draining (minus the terminal
            # flag): the dialer fails over in milliseconds instead of
            # timing out in its handshake against a silent close.
            await self._refuse_peer(conn, "provider at connection limit")
            return
        peer = await Peer.connect(conn, self.identity, initiator=False)
        self._client_peers.add(peer)
        self._m_connections.set(len(self._client_peers))
        await self._report_connections()
        peer_key = peer.remote_public_hex
        logger.debug(f"client peer connected: {peer_key[:12]}")
        try:
            async for msg in peer:
                if msg.key == MessageKey.NEW_CONVERSATION:
                    # src/provider.ts:181-183
                    self._conversation_index[peer_key] = (
                        self._conversation_index.get(peer_key, 0) + 1
                    )
                elif msg.key == MessageKey.INFERENCE:
                    data = msg.data or {}
                    req_id = data.get("requestId")
                    peer_load = sum(1 for (pid, _) in self._inference_tasks
                                    if pid == id(peer))
                    if req_id and (id(peer), str(req_id)) in                             self._inference_tasks:
                        # duplicate id: accepting it would overwrite the
                        # task entry (bypassing the cap below, orphaning
                        # the first task's cancel handle) and interleave
                        # two streams into one client queue
                        await peer.send(MessageKey.INFERENCE_ERROR, {
                            "error": "duplicate requestId",
                            "requestId": req_id})
                    elif req_id and peer_load >= self.config.get(
                            "maxConcurrentRequests", 32):
                        # multiplexing removed the implicit one-per-peer
                        # serialization; an explicit PER-PEER cap replaces
                        # it so one client's request flood cannot spawn
                        # unbounded tasks (other peers are unaffected —
                        # their aggregate is already bounded by
                        # maxConnections × this cap)
                        await peer.send(MessageKey.INFERENCE_ERROR, {
                            "error": "too many concurrent requests",
                            "requestId": req_id})
                    elif req_id:
                        # Multiplexed mode (round-2 verdict weak #8: the
                        # wire lacked request ids, forcing one in-flight
                        # chat per peer): each request pumps in its own
                        # task, stream messages echo the id, the client
                        # demultiplexes.
                        key = (id(peer), str(req_id))
                        task = self._spawn(
                            self._handle_inference(peer, data))
                        self._inference_tasks[key] = task
                        task.add_done_callback(
                            lambda _t, k=key:
                            self._inference_tasks.pop(k, None))
                    else:
                        # legacy: one at a time, in-order (reference
                        # parity, src/provider.ts:195)
                        await self._handle_inference(peer, data)
                elif msg.key == MessageKey.INFERENCE_CANCEL:
                    req_id = str((msg.data or {}).get("requestId", ""))
                    task = self._inference_tasks.get((id(peer), req_id))
                    if task is not None:
                        task.cancel()
                elif msg.key == MessageKey.PING:
                    await peer.send(MessageKey.PONG)
                elif msg.key == MessageKey.METRICS:
                    # Clients may query the serving snapshot (tok/s, TTFT
                    # percentiles) — same payload the server receives —
                    # plus the engine scheduler's own breakdown when the
                    # backend exposes one (tpu_native.engine_stats), so a
                    # wire-side stall can be attributed engine vs relay.
                    payload = self.stats()
                    engine_stats = getattr(self.backend, "engine_stats",
                                           None)
                    if engine_stats is not None:
                        with contextlib.suppress(Exception):
                            payload["engine"] = await engine_stats()
                    if METRICS.enabled:
                        # The registry snapshots (this process + the
                        # engine host(s), tier-labeled) ride the same
                        # reply — the swarm path's scrape surface, no
                        # open port required (symtop's wire mode,
                        # bench --metrics-out).
                        with contextlib.suppress(Exception):
                            payload["metrics"] = {
                                "snapshots":
                                    await self.metrics_snapshots()}
                    await peer.send(MessageKey.METRICS, payload)
                elif msg.key == MessageKey.TRACE:
                    # Merged span-ring snapshot (provider + backend/host/
                    # scheduler components) for the client-side Perfetto
                    # export — the request-tracing analog of METRICS.
                    await peer.send(MessageKey.TRACE,
                                    await self.gather_trace())
                elif msg.key == MessageKey.PROFILE:
                    # On-demand device profile: run one bounded
                    # jax.profiler capture on the engine and reply with
                    # the artifact path (or a structured error). SPAWNED
                    # like an inference — the capture (plus the
                    # process's first-capture cold init) spans tens of
                    # seconds, and awaiting it inline would stall THIS
                    # peer's whole message loop: submits unread, cancels
                    # undelivered, pings unanswered for the window. The
                    # window itself is clamped — durationS is
                    # client-supplied and must not pin the single-flight
                    # capture slot indefinitely.
                    d = (msg.data or {}).get("durationS")
                    try:
                        d = min(float(d), 120.0) if d is not None else None
                    except (TypeError, ValueError):
                        d = None

                    async def _profile_reply(peer=peer,
                                             duration_s=d) -> None:
                        out = await self._capture_profile(
                            "wire", duration_s=duration_s)
                        with contextlib.suppress(ConnectionError,
                                                 OSError):
                            await peer.send(MessageKey.PROFILE, out)

                    self._spawn(_profile_reply())
                elif msg.key == MessageKey.LEAVE:
                    break
        finally:
            self._client_peers.discard(peer)
            self._m_connections.set(len(self._client_peers))
            await peer.close()
            # Fold AFTER close: the cork's settle() may perform one last
            # write on the way down, and it must land in the totals.
            ws = peer.write_stats
            if ws:
                for k in self._wire_totals:
                    self._wire_totals[k] += ws.get(k, 0)
            await self._report_connections()

    # ----- the hot path (reference: handleInferenceRequest, src/provider.ts:195-275) -----

    def _check_session(self, peer: Peer, data: dict) -> str | None:
        """Validate the session token offline against the trusted serverKey.

        Private providers (public: false) accept direct unsessioned peers, as
        the reference's direct-connection mode does.
        """
        if not self.config.public or not self.config.get("requireSessions", True):
            return None
        payload = session_tokens.verify(
            data.get("sessionToken"),
            self.config.server_key,
            client_key=peer.remote_public_hex,
            model_name=self.config.model_name,
        )
        if payload is None:
            return "invalid or expired session token"
        return None

    def _estimated_first_token_wait_s(self) -> float | None:
        """Predicted first-token wait for a request admitted NOW: requests
        already accepted but not yet streaming, divided by the recent
        first-token rate. None = no recent rate signal — a burst from idle
        must not be shed on ignorance (the signal appears as soon as its
        first wave starts streaming)."""
        if self._unstarted <= 0:
            return 0.0
        now = time.monotonic()
        recent = [t for t in self._first_token_stamps if now - t < 10.0]
        if len(recent) < 4:
            return None
        span = max(now - recent[0], 0.25)
        return self._unstarted / (len(recent) / span)

    def _admission_shed_reason(self) -> dict | None:
        """The structured busy payload when a new request must be shed,
        else None. Two independent bounds:

        1. in-flight ≥ queue_limit — the backlog exceeds ~one extra slot
           rotation, so TTFT would grow with queue depth;
        2. estimated first-token wait > admission_ttft_bound_s — the
           sustained-arrival mode where decode slots may still be free but
           prefill dispatch rate is the limiter and the scheduler inbox
           holds seconds of wait (the in-flight bound can't see this).
        """
        limit = getattr(self.backend, "queue_limit", None)
        slots = getattr(self.backend, "slots", None) or 0
        if limit is not None and self._in_flight >= limit:
            return {"error": f"provider busy: {self._in_flight} requests "
                             f"in flight (limit {limit})",
                    "queueDepth": max(0, self._in_flight - slots),
                    "queueLimit": limit}
        bound = getattr(self.backend, "admission_ttft_bound_s", None)
        if bound is not None:
            est = self._estimated_first_token_wait_s()
            if est is not None and est > bound:
                return {"error": f"provider busy: estimated first-token "
                                 f"wait {est:.1f}s exceeds {bound:.1f}s",
                        "queueDepth": self._unstarted,
                        "estimatedWaitS": round(est, 2),
                        **({"queueLimit": limit}
                           if limit is not None else {})}
        return None

    async def _shed(self, peer: Peer, tag: dict, reason: dict) -> None:
        self.metrics["shed"] += 1
        self._m_sheds.inc(reason="busy")
        logger.debug(f"shedding request: {reason['error']}")
        await peer.send(MessageKey.INFERENCE_ERROR,
                        {**reason, "busy": True, **tag})
        # Push the load report NOW (throttled): the 15 s health-loop
        # cadence is too stale for the router to steer a burst away.
        now = time.monotonic()
        if (now - self._last_load_report > 2.0
                and self._server_peer is not None
                and not self._server_peer.closed):
            self._last_load_report = now
            with contextlib.suppress(ConnectionError, OSError):
                await self._server_peer.send(MessageKey.METRICS,
                                             self.stats())

    def _pending_gauges(self) -> None:
        self._m_in_flight.set(self._in_flight)
        self._m_pending_first.set(max(self._unstarted, 0))

    async def _handle_inference(self, peer: Peer, data: dict) -> None:
        start = time.monotonic()
        req_id = data.get("requestId")
        # echoed on every message of this stream so a multiplexing client
        # can route chunks; absent for legacy single-stream peers
        tag = {"requestId": req_id} if req_id else {}
        messages = data.get("messages")
        if not isinstance(messages, list):
            await peer.send(MessageKey.INFERENCE_ERROR,
                            {"error": "missing messages", **tag})
            return
        err = self._check_session(peer, data)
        if err is not None:
            await peer.send(MessageKey.INFERENCE_ERROR,
                            {"error": err, **tag})
            return
        # Bounded-latency admission: a request the provider cannot serve
        # within its latency bounds is shed NOW with a STRUCTURED busy
        # error — the client fails over (chat_failover excludes this
        # provider), and the router steers by the queue depth reported in
        # stats/METRICS. The reference had no equivalent (only the
        # maxConnections peer cap, src/provider.ts:38-40): every queued
        # client just waited, p99 growing with the backlog.
        shed_reason = self._admission_shed_reason()
        if shed_reason is not None:
            await self._shed(peer, tag, shed_reason)
            return
        deadline_s = data.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                await peer.send(MessageKey.INFERENCE_ERROR,
                                {"error": "invalid deadline_s", **tag})
                return
            if deadline_s <= 0:
                # Already expired on arrival: shed without touching the
                # backend. NOT retryable (no "busy") — by definition the
                # caller stopped waiting, so failover would only burn
                # another provider's admission slot.
                self.metrics["shed"] += 1
                self._m_sheds.inc(reason="expired")
                await peer.send(MessageKey.INFERENCE_ERROR,
                                {"error": "deadline_s already expired",
                                 "expired": True, **tag})
                return
        resume = data.get("resume")
        resume_text: str | None = None
        resume_tokens: int | None = None
        if isinstance(resume, dict) and resume.get("text"):
            # Stream resumption: the client holds a partial completion
            # from a provider that died mid-stream and asks THIS one to
            # continue from its end. A backend that would regenerate
            # from scratch is refused with a structured marker — the
            # client then falls back to a from-scratch restart instead
            # of splicing a duplicate completion onto its partial text.
            if not getattr(self.backend, "supports_resume", False):
                self._m_resumes.inc(outcome="refused")
                await peer.send(MessageKey.INFERENCE_ERROR,
                                {"error": "backend does not support "
                                          "stream resumption",
                                 "resumeUnsupported": True, **tag})
                return
            resume_text = str(resume.get("text"))
            rt = resume.get("tokens")
            if rt is not None:
                try:
                    resume_tokens = int(rt)
                except (TypeError, ValueError):
                    resume_tokens = -1
                if resume_tokens < 0:
                    # Rejected at ingress for EVERY backend shape: a
                    # negative claim would inflate the token budget
                    # past the client's own max_tokens downstream.
                    await peer.send(MessageKey.INFERENCE_ERROR,
                                    {"error": "invalid resume tokens",
                                     **tag})
                    return
            self._m_resumes.inc(outcome="accepted")
        spec = data.get("speculative")
        trace_id = str(data.get("traceId") or "")
        request = InferenceRequest(
            messages=messages,
            max_tokens=data.get("max_tokens"),
            temperature=data.get("temperature"),
            top_p=data.get("top_p"),
            top_k=data.get("top_k"),
            seed=data.get("seed"),
            speculative=spec if isinstance(spec, bool) else None,
            trace_id=trace_id,
            deadline_s=deadline_s,
            resume_text=resume_text,
            resume_tokens=resume_tokens,
        )
        self._in_flight += 1
        self._unstarted += 1
        self.metrics["requests"] += 1
        self._m_requests.inc()
        self._pending_gauges()
        request_id = f"{peer.remote_public_hex[:12]}:{self.metrics['requests']}"
        completion_parts: list[str] = []
        first_token_s: float | None = None
        # hoisted above the try: the cancel handler reports them, and a
        # cancellation can land before the stream loop assigns anything
        n_chunks = 0
        n_tokens = 0
        # symledger: the backend's cost block (terminal chunk rider) and
        # the worst inter-chunk stall — the gap input to this request's
        # SLO-attainment verdict.
        req_costs: dict | None = None
        max_gap_s = 0.0
        # Every log record of this request (including the backend's,
        # which runs inside this task) carries the trace/request ids —
        # logs and the Perfetto timeline then correlate by the same keys.
        ctx = log_context(trace_id=trace_id,
                          request_id=str(req_id or request_id))
        try:
            ctx.__enter__()
            # Stream-start marker (reference src/provider.ts:234-238).
            # tMono = our CLOCK_MONOTONIC at send: the client brackets it
            # with its own stamps — a piggybacked clock handshake, so its
            # spans land on our timeline without an extra round trip.
            await peer.send(
                MessageKey.INFERENCE,
                {"status": "start", "provider": self.backend.name,
                 "model": self.config.model_name,
                 "tMono": time.monotonic(), **tag},
            )
            last_chunk_at: float | None = None
            async for chunk in self.backend.stream(request):
                if peer.closed:
                    # Mid-stream client death tolerated (src/provider.ts:242,253-254).
                    logger.debug("client gone mid-stream; aborting pump")
                    break
                if FAULTS.enabled and await FAULTS.apoint("provider.relay"):
                    continue  # injected drop_frame: this chunk is lost
                if chunk.text:
                    completion_parts.append(chunk.text)
                    # Engine backends report exact per-chunk token counts
                    # (0 included — e.g. a finish flushing held-back
                    # bytes); proxies leave None and we fall back to the
                    # reference's one-chunk≈one-token accounting.
                    n_tokens += (chunk.tokens if chunk.tokens is not None
                                 else 1)
                    now_chunk = time.monotonic()
                    if first_token_s is None:
                        first_token_s = now_chunk - start
                        self.tracer.record("ttft", start, first_token_s,
                                           request_id=request_id,
                                           trace_id=trace_id)
                        self._unstarted -= 1
                        self._pending_gauges()
                        self._first_token_stamps.append(now_chunk)
                        self._m_ttft.observe(first_token_s)
                        if resume_text is not None:
                            # The recovery-latency headline: request
                            # receipt → first CONTINUATION token.
                            self._m_resume_ttft.observe(first_token_s)
                        self.slo.observe("ttft", first_token_s)
                    else:
                        # Inter-chunk gap: the stall any live stream saw
                        # between deltas — the r05 tail metric, now an
                        # always-on series and an SLO input.
                        gap = now_chunk - last_chunk_at
                        self._m_inter_chunk.observe(gap)
                        self.slo.observe("inter_chunk", gap)
                        max_gap_s = max(max_gap_s, gap)
                    last_chunk_at = now_chunk
                if self._ledger_on and chunk.costs is not None:
                    req_costs = chunk.costs
                # Raw passthrough; Connection.send awaits drain = backpressure
                # (reference's write/drain discipline, src/provider.ts:248-252).
                await peer.send(MessageKey.TOKEN_CHUNK,
                                {"raw": chunk.raw, **tag})
                n_chunks += 1
            completion = "".join(completion_parts)
            if not peer.closed:
                await peer.send(
                    MessageKey.INFERENCE_ENDED,
                    # symledger: the attributed cost block rides the end
                    # frame so the CLIENT sees what its request cost —
                    # absent (not empty) while tpu.ledger is off.
                    {"chunks": n_chunks, "tokens": n_tokens,
                     **({"costs": req_costs} if req_costs is not None
                        else {}),
                     **tag},
                )
            self.metrics["tokens_out"] += n_tokens
            if n_tokens:
                self._m_tokens_out.inc(n_tokens)
            e2e_s = time.monotonic() - start
            self._m_e2e.observe(e2e_s)
            self.slo.observe("e2e", e2e_s)
            if req_costs is not None:
                self._fold_request_cost(
                    req_costs, n_tokens,
                    attained=self._slo_attained(first_token_s, e2e_s,
                                                max_gap_s),
                    request_id=str(req_id or request_id))
            self.tracer.record("inference", start, e2e_s,
                               request_id=request_id, trace_id=trace_id,
                               tokens=n_tokens, chunks=n_chunks)
            if (self.flight is not None and self.flight.slo_e2e_s
                    and e2e_s > self.flight.slo_e2e_s):
                # Latency-SLO breach: capture the window that CONTAINS
                # the slow request while it is still in the rings.
                logger.warning(f"request {request_id} breached e2e SLO "
                               f"({e2e_s:.2f}s > "
                               f"{self.flight.slo_e2e_s:.2f}s)")
                self._spawn(self._flight_dump("slo"))
            # Data collection (reference: saveCompletion, src/provider.ts:277-297).
            peer_key = peer.remote_public_hex
            await self.collector.save(
                peer_key=peer_key,
                conversation_index=self._conversation_index.get(peer_key, 0),
                messages=messages,
                completion=completion,
            )
            await self._report_completion(data, n_tokens)
        except BackendRestartingError as exc:
            # Engine host crash/wedge: the STRUCTURED retryable shed —
            # the client fails over immediately and (after a backoff
            # round) may return once the supervisor finishes the respawn.
            # No per-stream flight dump: the supervisor's restart hook
            # already captured the death once, and N in-flight streams
            # must not race N dumps of the same window.
            # Counted as an ERROR (matching the legacy stats counter) —
            # not also a shed: the registry and stats() surfaces must
            # agree, and double-booking every restarting request under
            # sheds_total too would make shed+error sums double-count.
            self.metrics["errors"] += 1
            self._m_errors.inc()
            logger.error(f"backend restarting: {exc}")
            if not peer.closed:
                with contextlib.suppress(ConnectionError, OSError):
                    await peer.send(MessageKey.INFERENCE_ERROR,
                                    {"error": str(exc), "busy": True,
                                     "restarting": True,
                                     # Exact relayed-token count for the
                                     # client's resume: everything sent
                                     # before this ordered error frame
                                     # was delivered, so n_tokens IS
                                     # what the client holds. The
                                     # backend's journal stamp may
                                     # exceed it when pipe frames died
                                     # with the host — those tokens are
                                     # lost work the resume regenerates;
                                     # the gap rides as emittedEngine
                                     # (wasted-work observability, the
                                     # chaos round's numerator).
                                     "emitted": n_tokens,
                                     **({"emittedEngine": exc.emitted}
                                        if getattr(exc, "emitted", None)
                                        is not None
                                        and exc.emitted > n_tokens
                                        else {}),
                                     **({"retryAfterS":
                                         round(exc.retry_after_s, 3)}
                                        if exc.retry_after_s is not None
                                        else {}),
                                     **tag})
        except BackendDeadlineError as exc:
            # Deadline expired before service (scheduler admission shed):
            # terminal for this request, not a provider failure.
            self.metrics["shed"] += 1
            self._m_sheds.inc(reason="expired")
            logger.debug(f"deadline shed: {exc}")
            if not peer.closed:
                with contextlib.suppress(ConnectionError, OSError):
                    await peer.send(MessageKey.INFERENCE_ERROR,
                                    {"error": str(exc), "expired": True,
                                     **tag})
        except BackendError as exc:
            self.metrics["errors"] += 1
            self._m_errors.inc()
            logger.error(f"backend error: {exc}")
            if self.flight is not None:
                self._spawn(self._flight_dump("backend_error"))
            if not peer.closed:
                with contextlib.suppress(ConnectionError, OSError):
                    await peer.send(MessageKey.INFERENCE_ERROR,
                                    {"error": str(exc), **tag})
        except InjectedFault as exc:
            # A fault armed at a provider-level seam fired: simulate the
            # crash it stands in for — drop the client cold (no error
            # frame), exactly what a dying provider process would do.
            self.metrics["errors"] += 1
            self._m_errors.inc()
            logger.error(f"injected fault: {exc}; dropping peer")
            await peer.close()
        except asyncio.CancelledError:
            # inferenceCancel (or shutdown): closing the generator frees
            # the engine slot; tell the client the stream is over
            if not peer.closed:
                with contextlib.suppress(ConnectionError, OSError):
                    await peer.send(MessageKey.INFERENCE_ENDED,
                                    {"cancelled": True, "chunks": n_chunks,
                                     "tokens": n_tokens, **tag})
            raise
        finally:
            ctx.__exit__(None, None, None)
            self._in_flight -= 1
            if first_token_s is None:
                # Never started streaming (error/cancel before the first
                # token) — still waiting from the estimator's view.
                self._unstarted -= 1
            self._pending_gauges()

    def _slo_attained(self, ttft_s: float | None, e2e_s: float,
                      max_gap_s: float) -> bool:
        """One request's SLO verdict: every configured `slo:` target
        met. This is the goodput numerator's gate — a completion that
        blew its latency target is device time spent, not goodput. No
        targets configured ⇒ trivially attained (goodput degenerates to
        plain tokens per device second). A request that never streamed
        a token (ttft None) fails any TTFT target by definition."""
        targets = self.slo.targets
        if not targets:
            return True
        t = targets.get("ttft")
        if t is not None and (ttft_s is None or ttft_s > t):
            return False
        t = targets.get("e2e")
        if t is not None and e2e_s > t:
            return False
        t = targets.get("inter_chunk")
        if t is not None and max_gap_s > t:
            return False
        return True

    def _fold_request_cost(self, costs: dict, tokens: int, *,
                           attained: bool, request_id: str) -> None:
        """Fold one finished request's ledger block into the always-on
        families, the goodput window, and the backend's autoscale
        accumulator. Runs once per request, only when a cost block
        arrived (tpu.ledger on + engine-shaped backend)."""
        device = costs.get("device_s")
        if isinstance(device, dict):
            for phase, seconds in device.items():
                self._m_req_device_s.observe(float(seconds),
                                             phase=str(phase))
        wasted = costs.get("wasted_s")
        if isinstance(wasted, dict):
            for reason, seconds in wasted.items():
                self._m_req_wasted_s.inc(float(seconds),
                                         reason=str(reason))
        try:
            device_total = float(costs.get("device_total_s") or 0.0)
        except (TypeError, ValueError):
            device_total = 0.0
        self._goodput_window.append((int(tokens), device_total, attained))
        good = sum(t for t, _d, a in self._goodput_window if a)
        dev_s = sum(d for _t, d, _a in self._goodput_window)
        if dev_s > 0:
            self._m_goodput.set(round(good / dev_s, 3))
        self._cost_ring.append(
            {"id": request_id, "attained": attained, "tokens": tokens,
             **costs})
        # Autoscale goodput numerator (tpu_native pool mode): only an
        # attained request's tokens count toward the scale signal.
        note = getattr(self.backend, "note_request_cost", None)
        if note is not None:
            note(tokens if attained else 0, tokens, device_total)

    async def _report_completion(self, data: dict, tokens: int) -> None:
        token = data.get("sessionToken") or {}
        session_id = (token.get("payload") or {}).get("sessionId") if isinstance(token, dict) else None
        if self._server_peer is not None and not self._server_peer.closed:
            with contextlib.suppress(ConnectionError, OSError):
                await self._server_peer.send(
                    MessageKey.REPORT_COMPLETION,
                    {"sessionId": session_id, "tokens": tokens},
                )
