"""Provider CLI: `python -m symmetry_tpu.provider [-c path]`.

Parity with the reference bin (src/symmetry.ts:1-24): `-c/--config` defaults
to ~/.config/symmetry/provider.yaml; constructs the provider and serves until
SIGINT, then drains gracefully.
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from symmetry_tpu.provider.config import ConfigManager, default_config_path
from symmetry_tpu.provider.provider import SymmetryProvider
from symmetry_tpu.utils.logging import logger


async def run(config_path: str) -> None:
    provider = SymmetryProvider(ConfigManager(config_path))
    await provider.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    logger.info("draining and shutting down…")
    await provider.stop()


def main() -> None:
    parser = argparse.ArgumentParser(prog="symmetry-provider")
    parser.add_argument("-c", "--config", default=default_config_path(),
                        help="path to provider.yaml")
    args = parser.parse_args()
    asyncio.run(run(args.config))


if __name__ == "__main__":
    main()
