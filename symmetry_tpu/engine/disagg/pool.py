"""Elastic disaggregated pools: M-prefill × N-decode membership + placement.

PR 7/9 built ONE prefill × ONE decode pair; a pair makes each tier a
single point of failure — one node death sheds every in-flight migration
and the whole unit respawns. DistServe and Splitwise (PAPERS.md) both
show the production win is phase-specific POOLS: independently sized
tiers where a dead node is a capacity event, not an outage. This module
is the membership/placement brain of that generalization:

    clients ──▶ tpu_native backend (provider process)
                    │ PoolRouter.place()            least-loaded healthy
                    ▼                               prefill member
    prefill-0  prefill-1 … prefill-M-1    (PrefillNode each, own machine
      │ handoff frames over per-member     or inline; per-member
      ▼ DecodeLinks                        DecodeLink + supervision)
    PoolRouter.route_decode()             decode member by queue-depth /
      │                                    burn-rate gauges
      ▼
    decode-0   decode-1 … decode-N-1      (local engine hosts, each its
                                           own supervision domain)

The router is deliberately PURE STATE — no asyncio, no sockets, no
subprocesses. The backend owns the plumbing (links, host pipes, respawn
loops) and drives the router through a narrow verb set, which is what
makes every membership/placement rule unit-testable in microseconds:

  add_member / mark_joining / mark_healthy    join + hot-join/rejoin
  drain(member) -> bool                       no NEW placements; in-flight
                                              finishes (deliberate drain).
                                              REFUSED for the last
                                              placeable member of a tier —
                                              nothing may scale a tier to 0
  retire(member) -> bool                      scaled-down member leaves the
                                              registry for good (the
                                              autoscaler's drain-before-
                                              kill terminal); refused while
                                              in-flight work remains;
                                              chip-seconds are banked
  on_lost(member) -> [request ids]            node death / link loss: the
                                              in-flight work to RE-PLACE
                                              on a survivor (never failed
                                              outright; only when no
                                              survivor exists does the
                                              caller shed retryable)
  place(request) / route_decode(request)      placement decisions
  update_gauges(member, queue_depth, …)       telemetry feed (PR 10's
                                              gauges as the control
                                              signal)

Placement policy: cache-aware least-loaded — each member's base score
is its live in-flight count plus its last-reported queue-depth gauge,
MINUS the predicted radix-cache hit (in blocks) for this request on
that member, weighted by `tpu.pool_affinity_weight` and decayed by the
age of the member's last gossiped cache summary (SGLang's cache-aware
load balancing, PAPERS.md). Burn rate is the tie-break (scale AWAY
from the tier burning SLO budget), then lifetime placements
(round-robin among idle equals). The affinity term degrades, never
wedges: no request digests, no gossiped summary, a stale summary, or
gauges older than two heartbeat periods (a member that stopped
reporting) all collapse the score to pure load — exactly the pre-PR-17
policy. A pool of one degenerates exactly to the pair: the single
member takes every placement while healthy, and its loss leaves
nothing to re-place onto — the caller sheds structured-retryable, the
PR 7/9 behavior.

Membership states (one-way transitions except rejoin):

    joining ──▶ healthy ──▶ draining ──▶ lost
                   ▲  └──────────────────▶ lost
                   └── hot-(re)join ◀──────┘

All state changes land in the always-on metrics registry
(utils/metrics.py) so symtop and any Prometheus scrape see the pool:
member counts, per-node state, per-node placements, re-placements and
drains — churn is accounted, never silent.
"""

from __future__ import annotations

import time
from typing import Any

from symmetry_tpu.utils.metrics import METRICS, MetricName

PREFILL = "prefill"
DECODE = "decode"


class MemberState:
    """Pool-membership lifecycle states (wire-visible in stats/symtop)."""

    JOINING = "joining"    # link up / spawn started, not yet serving
    HEALTHY = "healthy"    # taking placements
    DRAINING = "draining"  # no new placements; in-flight finishes
    LOST = "lost"          # node death / link loss / left — capacity gone


# Gauge encoding for sym_pool_member_state (symtop decodes it back).
STATE_CODES = {MemberState.JOINING: 0, MemberState.HEALTHY: 1,
               MemberState.DRAINING: 2, MemberState.LOST: 3}


class PoolConfig:
    """The `tpu.disagg.pool` mapping. Present ⇒ pool mode; absent ⇒ the
    backend keeps the PR 7/9 pair semantics untouched.

    Keys:
      prefill     int M (inline/self-addressed members) or a list of
                  peer addresses to dial (one member per address)
      decode      int N — local decode engine hosts (default 1)
      heartbeat_s link keepalive period (ping/pong; 0 disables); also
                  the decode-member stats-probe/gauge-refresh period
    """

    def __init__(self, disagg: dict[str, Any] | None) -> None:
        d = (disagg or {}).get("pool") or {}
        self.enabled: bool = bool(d)
        prefill = d.get("prefill", 1)
        if isinstance(prefill, (list, tuple)):
            self.prefill_peers: list[str] | None = [str(p) for p in prefill]
            self.prefill_count: int = len(self.prefill_peers)
        else:
            self.prefill_peers = None
            self.prefill_count = max(int(prefill), 1)
        self.decode_count: int = max(int(d.get("decode", 1)), 1)
        self.heartbeat_s: float = float(d.get("heartbeat_s", 5.0))


class PoolMember:
    """One tier member's membership + load state (router-owned)."""

    __slots__ = ("member_id", "tier", "state", "in_flight", "placements",
                 "queue_depth", "burn_rate", "node_id", "joined_at",
                 "state_since", "losses", "restarts", "summary",
                 "summary_at", "gauges_at", "hit_blocks", "alive_since",
                 "chip_s")

    def __init__(self, member_id: str, tier: str) -> None:
        self.member_id = member_id
        self.tier = tier
        self.state = MemberState.JOINING
        self.in_flight: set[str] = set()   # request ids placed/adopted here
        self.placements = 0                # lifetime placements
        self.queue_depth = 0.0             # last gauge feed
        self.burn_rate = 0.0
        self.node_id: str | None = None    # peer-announced identity
        self.joined_at = time.monotonic()
        self.state_since = self.joined_at
        self.losses = 0                    # times this member went lost
        self.restarts = 0                  # per-member respawns (decode)
        # Cache-affinity state: the member's last gossiped radix-cache
        # summary (digest set), when it arrived, when the load gauges
        # last arrived (None = never — a member that stopped gossiping
        # must fall out of affinity scoring, not coast on stale data),
        # and the lifetime predicted-hit blocks banked by placements.
        self.summary: frozenset[str] | None = None
        self.summary_at: float | None = None
        self.gauges_at: float | None = None
        self.hit_blocks = 0
        # Chip-second accounting (the autoscaler's goodput denominator):
        # `chip_s` accumulates completed alive intervals; `alive_since`
        # is the open interval's start (router clock), None while lost.
        # The router stamps these — the member never reads a clock.
        self.alive_since: float | None = None
        self.chip_s = 0.0

    @property
    def placeable(self) -> bool:
        return self.state == MemberState.HEALTHY

    def score(self) -> tuple:
        """Lower places first: live load + reported backlog, SLO burn as
        the tie-break, lifetime placements as round-robin among idle
        equals, member id for determinism."""
        return (len(self.in_flight) + self.queue_depth, self.burn_rate,
                self.placements, self.member_id)

    def to_dict(self) -> dict[str, Any]:
        return {"tier": self.tier, "state": self.state,
                "node": self.node_id, "in_flight": len(self.in_flight),
                "placements": self.placements,
                "queue_depth": self.queue_depth,
                "burn_rate": round(self.burn_rate, 4),
                "losses": self.losses, "restarts": self.restarts,
                "hit_blocks": self.hit_blocks,
                "summary_digests": (len(self.summary)
                                    if self.summary is not None else 0),
                "state_age_s": round(
                    time.monotonic() - self.state_since, 3)}


class PoolRouter:
    """Membership registry + placement for one elastic disagg pool.

    Thread contract: all calls happen on the backend's event loop (the
    link callbacks, the readers, and stream() all live there) — same
    no-locking contract as the broker."""

    def __init__(self, *, heartbeat_s: float = 5.0,
                 affinity_weight: float = 1.0,
                 clock=time.monotonic) -> None:
        # Affinity knobs: heartbeat_s sets the staleness clock for the
        # gossiped summaries AND the gauge-age cutoff (2 periods);
        # affinity_weight scales predicted-hit blocks against load
        # (queue slots) — 0 turns cache-aware placement off entirely.
        # `clock` is injectable so staleness decay is test-drivable.
        self.heartbeat_s = max(float(heartbeat_s), 0.001)
        self.affinity_weight = max(float(affinity_weight), 0.0)
        self._clock = clock
        self._members: dict[str, PoolMember] = {}
        # request id -> member id, per tier (a request is assigned to at
        # most one prefill member pre-handoff, one decode member after).
        self._assigned: dict[str, str] = {}
        self._adopted: dict[str, str] = {}
        # request id -> decode member chosen AT SUBMIT TIME (so the
        # prefill tier can key its shipped-block ledger by the member
        # the handoff will actually reach); consumed by route_decode.
        self._planned: dict[str, str] = {}
        # Per-member ledger epoch: bumped every time the member goes
        # lost. The prefill tier tags ledger entries with the epoch it
        # was told at submit; a bumped epoch invalidates every entry
        # (the respawned member's cache is empty — skipping blocks it
        # no longer holds would corrupt adoption).
        self._ledger_epoch: dict[str, int] = {}
        self.counters = {"placements": 0, "re_placements": 0,
                         "drains": 0, "drain_refused": 0, "retires": 0,
                         "losses": 0, "joins": 0,
                         "rejoins": 0, "affinity_hit": 0,
                         "affinity_cold": 0, "affinity_load_only": 0}
        # Chip-seconds already banked by members retired out of the
        # registry (scale-down) — live members' alive time stays on the
        # member until then. chip_seconds() sums both.
        self._chip_s_retired = 0.0
        self._m_members = METRICS.gauge(
            MetricName.POOL_MEMBERS, "pool members known (any state)",
            labels=("tier",))
        self._m_healthy = METRICS.gauge(
            MetricName.POOL_HEALTHY, "pool members taking placements",
            labels=("tier",))
        self._m_state = METRICS.gauge(
            MetricName.POOL_MEMBER_STATE,
            "per-member state (0 joining, 1 healthy, 2 draining, 3 lost)",
            labels=("tier", "node"))
        self._m_placements = METRICS.counter(
            MetricName.POOL_PLACEMENTS, "requests placed on a member",
            labels=("tier", "node"))
        self._m_replacements = METRICS.counter(
            MetricName.POOL_REPLACEMENTS,
            "in-flight requests re-placed off a lost/drained member")
        self._m_drains = METRICS.counter(
            MetricName.POOL_DRAINS, "members drained (deliberate)")
        self._m_predicted_hit = METRICS.counter(
            MetricName.POOL_PREDICTED_HIT,
            "predicted radix-hit blocks banked by affinity placements",
            labels=("tier", "node"))
        self._m_affinity = METRICS.counter(
            MetricName.POOL_AFFINITY_PLACEMENTS,
            "placements by affinity outcome (hit/cold/load_only)",
            labels=("outcome",))
        self._m_gossip_age = METRICS.gauge(
            MetricName.POOL_GOSSIP_AGE,
            "age of a member's last gossiped cache summary",
            labels=("tier", "node"))

    # --------------------------------------------------------- membership

    def members(self, tier: str | None = None) -> list[PoolMember]:
        return [m for m in self._members.values()
                if tier is None or m.tier == tier]

    def get(self, member_id: str) -> PoolMember | None:
        return self._members.get(member_id)

    def add_member(self, member_id: str, tier: str,
                   node_id: str | None = None) -> PoolMember:
        if tier not in (PREFILL, DECODE):
            raise ValueError(f"pool member tier must be prefill|decode, "
                             f"got {tier!r}")
        m = self._members.get(member_id)
        if m is None:
            m = PoolMember(member_id, tier)
            m.alive_since = self._clock()
            self._members[member_id] = m
        if node_id:
            m.node_id = node_id
        self._refresh_gauges(m)
        return m

    def _set_state(self, m: PoolMember, state: str) -> None:
        if m.state != state:
            # Chip-seconds tick only while the member is not lost: close
            # the open alive interval on the way INTO lost, open a new
            # one on the way out (rejoin). Joining/draining still count —
            # a spawning or draining member occupies its chip.
            now = self._clock()
            if state == MemberState.LOST and m.alive_since is not None:
                m.chip_s += max(now - m.alive_since, 0.0)
                m.alive_since = None
            elif m.state == MemberState.LOST and m.alive_since is None:
                m.alive_since = now
            m.state = state
            m.state_since = time.monotonic()
        self._refresh_gauges(m)

    def mark_joining(self, member_id: str) -> None:
        m = self._members[member_id]
        self._set_state(m, MemberState.JOINING)

    def mark_healthy(self, member_id: str,
                     node_id: str | None = None) -> None:
        """Member is serving: first join, hot-join, or rejoin after a
        loss — churn in, not a special case."""
        m = self._members[member_id]
        if node_id:
            m.node_id = node_id
        if m.state == MemberState.LOST:
            self.counters["rejoins"] += 1
            # A rejoined member is a NEW process with an empty cache and
            # no load history. Pre-PR-17 the router kept trusting the
            # pre-loss gauges forever; now everything resets and the
            # member is load-only (gauges_at None) until its first fresh
            # heartbeat stamps it back into affinity scoring.
            m.queue_depth = 0.0
            m.burn_rate = 0.0
            m.gauges_at = None
            m.summary = None
            m.summary_at = None
        elif m.state == MemberState.JOINING:
            self.counters["joins"] += 1
        self._set_state(m, MemberState.HEALTHY)

    def drain(self, member_id: str) -> bool:
        """Deliberate drain: excluded from NEW placements immediately;
        whatever is in flight finishes (or is re-placed by on_lost if
        the node dies mid-drain). REFUSED (returns False) when this is
        the LAST placeable member of its tier — a drain there is a
        self-inflicted outage, and the autoscaler (or an operator) must
        never be able to scale a tier to zero; the caller retries after
        a replacement joins. Returns True when the member is draining
        (including when it already was)."""
        m = self._members[member_id]
        if m.state in (MemberState.DRAINING, MemberState.LOST):
            return True
        if m.placeable and self.healthy_count(m.tier) <= 1:
            self.counters["drain_refused"] += 1
            return False
        self.counters["drains"] += 1
        self._m_drains.inc()
        self._set_state(m, MemberState.DRAINING)
        return True

    def on_lost(self, member_id: str) -> list[str]:
        """Node death / link loss / leave: capacity is gone NOW. Returns
        the request ids that were in flight there — the caller re-places
        each on a survivor (or sheds structured-retryable when none
        exists). Idempotent: a second loss signal returns []."""
        m = self._members.get(member_id)
        if m is None:
            return []
        if m.state != MemberState.LOST:
            m.losses += 1
            self.counters["losses"] += 1
            # Its cache died with it: invalidate the gossiped summary
            # (no more affinity pulls toward a cold respawn) and bump
            # the ledger epoch so the prefill tier drops every
            # shipped-block entry keyed to this member.
            m.summary = None
            m.summary_at = None
            m.gauges_at = None
            self._ledger_epoch[member_id] = (
                self._ledger_epoch.get(member_id, 0) + 1)
        self._set_state(m, MemberState.LOST)
        ids = sorted(m.in_flight)
        m.in_flight.clear()
        for req_id in ids:
            if self._assigned.get(req_id) == member_id:
                self._assigned.pop(req_id, None)
            if self._adopted.get(req_id) == member_id:
                self._adopted.pop(req_id, None)
            if self._planned.get(req_id) == member_id:
                self._planned.pop(req_id, None)
        return ids

    def retire(self, member_id: str) -> bool:
        """Remove a scaled-down member from the registry for good —
        the terminal verb of a deliberate drain (the autoscaler's
        drain-before-kill path), NOT of a loss: a lost member stays
        registered so a rejoin finds its slot. Refused (False) while
        the member still has in-flight work — retire only after the
        drain ran dry. Banks the member's chip-seconds into the
        retired total so the goodput denominator never loses the time
        a scaled-away member burned."""
        m = self._members.get(member_id)
        if m is None:
            return True
        if m.in_flight:
            return False
        now = self._clock()
        self._chip_s_retired += m.chip_s + (
            max(now - m.alive_since, 0.0)
            if m.alive_since is not None else 0.0)
        self.counters["retires"] += 1
        del self._members[member_id]
        self._ledger_epoch.pop(member_id, None)
        # Drop the per-member state series (a gauge for a retired
        # member would export its last state forever) and recompute the
        # tier counts it was part of.
        self._m_state.remove(tier=m.tier, node=m.member_id)
        for tier in (PREFILL, DECODE):
            members = self.members(tier)
            self._m_members.set(len(members), tier=tier)
            self._m_healthy.set(
                sum(1 for x in members if x.placeable), tier=tier)
        return True

    def member_chip_s(self, m: PoolMember) -> float:
        """One member's chip-seconds so far: banked intervals plus the
        open alive interval (router clock)."""
        live = (max(self._clock() - m.alive_since, 0.0)
                if m.alive_since is not None else 0.0)
        return m.chip_s + live

    def chip_seconds(self) -> float:
        """Σ member-alive time across the pool's whole history —
        retired members included. The denominator of SLO-goodput
        (tokens per chip-second): scaling up buys capacity at the cost
        of a faster-growing denominator, which is exactly the trade the
        autoscaler is scored on."""
        return self._chip_s_retired + sum(
            self.member_chip_s(m) for m in self._members.values())

    def ledger_epoch(self, member_id: str) -> int:
        """Current shipped-block-ledger epoch for a member (0 until its
        first loss). Rides each submit so the prefill host can detect a
        member respawn and drop that member's ledger."""
        return self._ledger_epoch.get(member_id, 0)

    # --------------------------------------------------------- placement

    def predicted_hit(self, m: PoolMember,
                      digests: list[str] | None) -> int:
        """Predicted radix-cache hit depth (blocks) for a request with
        these causal block digests on this member: the longest
        CONTIGUOUS leading run of the request's digests present in the
        member's gossiped summary. Contiguous because the radix tree
        can only serve a prefix — digest k without digests 0..k-1 is
        unreachable KV. 0 whenever the signal is unusable: no digests,
        no summary, or gauges older than two heartbeat periods (the
        member stopped reporting; its summary describes a past life)."""
        if (not digests or m.summary is None
                or not self._gauges_fresh(m)):
            return 0
        hit = 0
        for d in digests:
            if d not in m.summary:
                break
            hit += 1
        return hit

    def _gauges_fresh(self, m: PoolMember) -> bool:
        return (m.gauges_at is not None
                and self._clock() - m.gauges_at
                <= 2.0 * self.heartbeat_s)

    def _summary_decay(self, m: PoolMember) -> float:
        """Staleness decay on the gossiped summary: halves every two
        heartbeat periods, so a member that keeps gossiping scores near
        full weight and one whose summary is aging fades smoothly out
        of affinity instead of flapping."""
        if m.summary_at is None:
            return 0.0
        age = max(self._clock() - m.summary_at, 0.0)
        return 0.5 ** (age / (2.0 * self.heartbeat_s))

    def _pick(self, tier: str,
              exclude: set[str] | frozenset = frozenset(),
              digests: list[str] | None = None
              ) -> tuple[PoolMember | None, int]:
        """Best placeable member of `tier` and its predicted-hit depth
        (blocks). Score = load − affinity_weight × decay × hit, so one
        decayed hit block outbids one queue slot at weight 1 — then the
        original burn/placements/id tie-break. With no usable affinity
        signal every hit term is 0 and this IS the pre-PR-17 policy."""
        live = [m for m in self._members.values()
                if m.tier == tier and m.placeable
                and m.member_id not in exclude]
        if not live:
            return None, 0
        use_affinity = bool(digests) and self.affinity_weight > 0.0
        # Outstanding decode plans are load the member WILL carry (the
        # handoff lands there) — without them every concurrent submit
        # would plan the same idle member by id tie-break.
        planned: dict[str, int] = {}
        for mid in self._planned.values():
            planned[mid] = planned.get(mid, 0) + 1
        best: PoolMember | None = None
        best_key: tuple | None = None
        best_hit = 0
        for m in live:
            hit = self.predicted_hit(m, digests) if use_affinity else 0
            key = (len(m.in_flight) + m.queue_depth
                   + planned.get(m.member_id, 0)
                   - self.affinity_weight * self._summary_decay(m) * hit,
                   m.burn_rate, m.placements, m.member_id)
            if best_key is None or key < best_key:
                best, best_key, best_hit = m, key, hit
        return best, best_hit

    def _book_affinity(self, m: PoolMember, tier: str, hit: int,
                       digests: list[str] | None) -> None:
        """Account one placement's affinity outcome: `hit` (the summary
        predicted cached blocks on the winner), `cold` (a signal
        existed but predicted nothing — e.g. turn 1, or the warm member
        died), `load_only` (no usable signal at all)."""
        if not digests or self.affinity_weight <= 0.0:
            outcome = "load_only"
        elif hit > 0:
            outcome = "hit"
            m.hit_blocks += hit
            self._m_predicted_hit.inc(hit, tier=tier, node=m.member_id)
        else:
            outcome = "cold"
        self.counters[f"affinity_{outcome}"] = (
            self.counters.get(f"affinity_{outcome}", 0) + 1)
        self._m_affinity.inc(outcome=outcome)

    def place(self, request_id: str, *,
              digests: list[str] | None = None,
              exclude: set[str] | frozenset = frozenset()) -> str | None:
        """Best healthy PREFILL member for one request — cache-affine
        when `digests` (the request's causal block digests) are given,
        least-loaded otherwise; None when no member is placeable
        (caller sheds retryable). ASSIGNS only — the caller confirms
        with record_placement() once the submit actually reached the
        member, so a refused send (walked past via `exclude` +
        release()) never inflates the ledger or skews the round-robin
        tie-break."""
        m, hit = self._pick(PREFILL, exclude, digests)
        if m is None:
            return None
        self._book_affinity(m, PREFILL, hit, digests)
        old = self._assigned.get(request_id)
        if old is not None and old != m.member_id:
            prev = self._members.get(old)
            if prev is not None:
                prev.in_flight.discard(request_id)
        self._assigned[request_id] = m.member_id
        m.in_flight.add(request_id)
        self._refresh_gauges(m)
        return m.member_id

    def plan_decode(self, request_id: str,
                    digests: list[str] | None = None) -> str | None:
        """Choose (but do not yet book) the decode member this request's
        handoff should land on — cache-affine against the DECODE tier's
        gossiped summaries. Called at submit time so the prefill host
        can key its shipped-block ledger by the actual destination;
        route_decode() consumes the plan when the handoff arrives (and
        re-picks if that member died in between). None when no decode
        member is placeable (single-decode pools always plan the one)."""
        m, _hit = self._pick(DECODE, frozenset(), digests)
        if m is None:
            self._planned.pop(request_id, None)
            return None
        self._planned[request_id] = m.member_id
        return m.member_id

    def planned_decode(self, request_id: str) -> str | None:
        return self._planned.get(request_id)

    def record_placement(self, request_id: str, *,
                         replacement: bool = False) -> None:
        """The placed submit reached its member: book the placement
        (and the re-placement, when this was churn recovery) in the
        counters, the per-node metric, and the tie-break state."""
        member_id = self._assigned.get(request_id)
        m = self._members.get(member_id) if member_id else None
        if m is None:
            return
        m.placements += 1
        self.counters["placements"] += 1
        self._m_placements.inc(tier=PREFILL, node=m.member_id)
        if replacement:
            self.counters["re_placements"] += 1
            self._m_replacements.inc()

    def route_decode(self, request_id: str, *,
                     prefer: str | None = None) -> str | None:
        """DECODE member for one handed-off request; releases the
        prefill assignment (the migration left that tier). Prefers the
        member planned at submit time (`prefer` or the stored plan) —
        the one the shipped-block ledger was keyed against — falling
        back to the gauge-scored pick when that member is no longer
        placeable. None when no decode member is placeable."""
        self._release_assigned(request_id)
        planned = self._planned.pop(request_id, None)
        prefer = prefer or planned
        m: PoolMember | None = None
        if prefer is not None:
            cand = self._members.get(prefer)
            if cand is not None and cand.tier == DECODE and cand.placeable:
                m = cand
        if m is None:
            m, _hit = self._pick(DECODE)
        if m is None:
            return None
        self._adopted[request_id] = m.member_id
        m.in_flight.add(request_id)
        m.placements += 1
        self.counters["placements"] += 1
        self._m_placements.inc(tier=DECODE, node=m.member_id)
        self._refresh_gauges(m)
        return m.member_id

    def assigned_to(self, request_id: str) -> str | None:
        return self._assigned.get(request_id)

    def adopted_on(self, request_id: str) -> str | None:
        return self._adopted.get(request_id)

    def release(self, request_id: str) -> None:
        """Undo a placement that never reached the member (send
        failed): the assignment is dropped without counting a loss."""
        self._release_assigned(request_id)

    def _release_assigned(self, request_id: str) -> None:
        member_id = self._assigned.pop(request_id, None)
        if member_id is not None:
            m = self._members.get(member_id)
            if m is not None:
                m.in_flight.discard(request_id)
                self._refresh_gauges(m)

    def note_done(self, request_id: str) -> None:
        """Request ended (any outcome): release whatever it held."""
        self._release_assigned(request_id)
        self._planned.pop(request_id, None)
        member_id = self._adopted.pop(request_id, None)
        if member_id is not None:
            m = self._members.get(member_id)
            if m is not None:
                m.in_flight.discard(request_id)
                self._refresh_gauges(m)

    # ---------------------------------------------------------- telemetry

    def update_gauges(self, member_id: str, *,
                      queue_depth: float | None = None,
                      burn_rate: float | None = None) -> None:
        """Feed one member's load gauges (scheduler queue depth off its
        stats probe; SLO burn rate from the provider's monitor) — the
        placement signal beyond the router's own in-flight counts.
        Stamps the gauge age: a member whose stamp falls more than two
        heartbeat periods behind drops out of affinity scoring (its
        summary describes a cache we can no longer see)."""
        m = self._members.get(member_id)
        if m is None:
            return
        if queue_depth is not None:
            m.queue_depth = max(float(queue_depth), 0.0)
        if burn_rate is not None:
            m.burn_rate = max(float(burn_rate), 0.0)
        m.gauges_at = self._clock()
        if m.summary_at is not None:
            self._m_gossip_age.set(
                round(max(self._clock() - m.summary_at, 0.0), 3),
                tier=m.tier, node=m.member_id)

    def update_summary(self, member_id: str,
                       summary: dict[str, Any] | None) -> None:
        """Feed one member's gossiped radix-cache summary (the stats
        rider harvested off its heartbeat probe). None means the member
        answered without a summary (cache disabled, empty, or an old
        binary) — keep the previous one aging out via decay rather than
        flapping the affinity signal on every empty beat."""
        m = self._members.get(member_id)
        if m is None or summary is None:
            return
        digests = summary.get("digests")
        if not isinstance(digests, (list, tuple)) or not digests:
            return
        m.summary = frozenset(str(d) for d in digests)
        m.summary_at = self._clock()
        self._m_gossip_age.set(0.0, tier=m.tier, node=m.member_id)

    def _refresh_gauges(self, m: PoolMember) -> None:
        self._m_state.set(STATE_CODES[m.state], tier=m.tier,
                          node=m.member_id)
        for tier in (PREFILL, DECODE):
            members = self.members(tier)
            self._m_members.set(len(members), tier=tier)
            self._m_healthy.set(
                sum(1 for x in members if x.placeable), tier=tier)

    # -------------------------------------------------------------- stats

    def healthy_count(self, tier: str) -> int:
        return sum(1 for m in self.members(tier) if m.placeable)

    def stats(self) -> dict[str, Any]:
        members = {}
        for mid, m in sorted(self._members.items()):
            d = m.to_dict()
            d["chip_s"] = round(self.member_chip_s(m), 3)
            members[mid] = d
        return {
            **self.counters,
            "members": members,
            "healthy": {PREFILL: self.healthy_count(PREFILL),
                        DECODE: self.healthy_count(DECODE)},
            "in_flight": {PREFILL: len(self._assigned),
                          DECODE: len(self._adopted)},
            "ledger_epochs": dict(sorted(self._ledger_epoch.items())),
            "chip_seconds": round(self.chip_seconds(), 3),
        }
