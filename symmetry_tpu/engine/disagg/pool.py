"""Elastic disaggregated pools: M-prefill × N-decode membership + placement.

PR 7/9 built ONE prefill × ONE decode pair; a pair makes each tier a
single point of failure — one node death sheds every in-flight migration
and the whole unit respawns. DistServe and Splitwise (PAPERS.md) both
show the production win is phase-specific POOLS: independently sized
tiers where a dead node is a capacity event, not an outage. This module
is the membership/placement brain of that generalization:

    clients ──▶ tpu_native backend (provider process)
                    │ PoolRouter.place()            least-loaded healthy
                    ▼                               prefill member
    prefill-0  prefill-1 … prefill-M-1    (PrefillNode each, own machine
      │ handoff frames over per-member     or inline; per-member
      ▼ DecodeLinks                        DecodeLink + supervision)
    PoolRouter.route_decode()             decode member by queue-depth /
      │                                    burn-rate gauges
      ▼
    decode-0   decode-1 … decode-N-1      (local engine hosts, each its
                                           own supervision domain)

The router is deliberately PURE STATE — no asyncio, no sockets, no
subprocesses. The backend owns the plumbing (links, host pipes, respawn
loops) and drives the router through a narrow verb set, which is what
makes every membership/placement rule unit-testable in microseconds:

  add_member / mark_joining / mark_healthy    join + hot-join/rejoin
  drain(member)                               no NEW placements; in-flight
                                              finishes (deliberate drain)
  on_lost(member) -> [request ids]            node death / link loss: the
                                              in-flight work to RE-PLACE
                                              on a survivor (never failed
                                              outright; only when no
                                              survivor exists does the
                                              caller shed retryable)
  place(request) / route_decode(request)      placement decisions
  update_gauges(member, queue_depth, …)       telemetry feed (PR 10's
                                              gauges as the control
                                              signal)

Placement policy: least-loaded healthy member — score is the member's
live in-flight count plus its last-reported queue-depth gauge, burn rate
as the tie-break (scale AWAY from the tier that is burning SLO budget),
then lifetime placements (round-robin among idle equals). A pool of one
degenerates exactly to the pair: the single member takes every placement
while healthy, and its loss leaves nothing to re-place onto — the caller
sheds structured-retryable, the PR 7/9 behavior.

Membership states (one-way transitions except rejoin):

    joining ──▶ healthy ──▶ draining ──▶ lost
                   ▲  └──────────────────▶ lost
                   └── hot-(re)join ◀──────┘

All state changes land in the always-on metrics registry
(utils/metrics.py) so symtop and any Prometheus scrape see the pool:
member counts, per-node state, per-node placements, re-placements and
drains — churn is accounted, never silent.
"""

from __future__ import annotations

import time
from typing import Any

from symmetry_tpu.utils.metrics import METRICS, MetricName

PREFILL = "prefill"
DECODE = "decode"


class MemberState:
    """Pool-membership lifecycle states (wire-visible in stats/symtop)."""

    JOINING = "joining"    # link up / spawn started, not yet serving
    HEALTHY = "healthy"    # taking placements
    DRAINING = "draining"  # no new placements; in-flight finishes
    LOST = "lost"          # node death / link loss / left — capacity gone


# Gauge encoding for sym_pool_member_state (symtop decodes it back).
STATE_CODES = {MemberState.JOINING: 0, MemberState.HEALTHY: 1,
               MemberState.DRAINING: 2, MemberState.LOST: 3}


class PoolConfig:
    """The `tpu.disagg.pool` mapping. Present ⇒ pool mode; absent ⇒ the
    backend keeps the PR 7/9 pair semantics untouched.

    Keys:
      prefill     int M (inline/self-addressed members) or a list of
                  peer addresses to dial (one member per address)
      decode      int N — local decode engine hosts (default 1)
      heartbeat_s link keepalive period (ping/pong; 0 disables); also
                  the decode-member stats-probe/gauge-refresh period
    """

    def __init__(self, disagg: dict[str, Any] | None) -> None:
        d = (disagg or {}).get("pool") or {}
        self.enabled: bool = bool(d)
        prefill = d.get("prefill", 1)
        if isinstance(prefill, (list, tuple)):
            self.prefill_peers: list[str] | None = [str(p) for p in prefill]
            self.prefill_count: int = len(self.prefill_peers)
        else:
            self.prefill_peers = None
            self.prefill_count = max(int(prefill), 1)
        self.decode_count: int = max(int(d.get("decode", 1)), 1)
        self.heartbeat_s: float = float(d.get("heartbeat_s", 5.0))


class PoolMember:
    """One tier member's membership + load state (router-owned)."""

    __slots__ = ("member_id", "tier", "state", "in_flight", "placements",
                 "queue_depth", "burn_rate", "node_id", "joined_at",
                 "state_since", "losses", "restarts")

    def __init__(self, member_id: str, tier: str) -> None:
        self.member_id = member_id
        self.tier = tier
        self.state = MemberState.JOINING
        self.in_flight: set[str] = set()   # request ids placed/adopted here
        self.placements = 0                # lifetime placements
        self.queue_depth = 0.0             # last gauge feed
        self.burn_rate = 0.0
        self.node_id: str | None = None    # peer-announced identity
        self.joined_at = time.monotonic()
        self.state_since = self.joined_at
        self.losses = 0                    # times this member went lost
        self.restarts = 0                  # per-member respawns (decode)

    @property
    def placeable(self) -> bool:
        return self.state == MemberState.HEALTHY

    def score(self) -> tuple:
        """Lower places first: live load + reported backlog, SLO burn as
        the tie-break, lifetime placements as round-robin among idle
        equals, member id for determinism."""
        return (len(self.in_flight) + self.queue_depth, self.burn_rate,
                self.placements, self.member_id)

    def to_dict(self) -> dict[str, Any]:
        return {"tier": self.tier, "state": self.state,
                "node": self.node_id, "in_flight": len(self.in_flight),
                "placements": self.placements,
                "queue_depth": self.queue_depth,
                "burn_rate": round(self.burn_rate, 4),
                "losses": self.losses, "restarts": self.restarts,
                "state_age_s": round(
                    time.monotonic() - self.state_since, 3)}


class PoolRouter:
    """Membership registry + placement for one elastic disagg pool.

    Thread contract: all calls happen on the backend's event loop (the
    link callbacks, the readers, and stream() all live there) — same
    no-locking contract as the broker."""

    def __init__(self) -> None:
        self._members: dict[str, PoolMember] = {}
        # request id -> member id, per tier (a request is assigned to at
        # most one prefill member pre-handoff, one decode member after).
        self._assigned: dict[str, str] = {}
        self._adopted: dict[str, str] = {}
        self.counters = {"placements": 0, "re_placements": 0,
                         "drains": 0, "losses": 0, "joins": 0,
                         "rejoins": 0}
        self._m_members = METRICS.gauge(
            MetricName.POOL_MEMBERS, "pool members known (any state)",
            labels=("tier",))
        self._m_healthy = METRICS.gauge(
            MetricName.POOL_HEALTHY, "pool members taking placements",
            labels=("tier",))
        self._m_state = METRICS.gauge(
            MetricName.POOL_MEMBER_STATE,
            "per-member state (0 joining, 1 healthy, 2 draining, 3 lost)",
            labels=("tier", "node"))
        self._m_placements = METRICS.counter(
            MetricName.POOL_PLACEMENTS, "requests placed on a member",
            labels=("tier", "node"))
        self._m_replacements = METRICS.counter(
            MetricName.POOL_REPLACEMENTS,
            "in-flight requests re-placed off a lost/drained member")
        self._m_drains = METRICS.counter(
            MetricName.POOL_DRAINS, "members drained (deliberate)")

    # --------------------------------------------------------- membership

    def members(self, tier: str | None = None) -> list[PoolMember]:
        return [m for m in self._members.values()
                if tier is None or m.tier == tier]

    def get(self, member_id: str) -> PoolMember | None:
        return self._members.get(member_id)

    def add_member(self, member_id: str, tier: str,
                   node_id: str | None = None) -> PoolMember:
        if tier not in (PREFILL, DECODE):
            raise ValueError(f"pool member tier must be prefill|decode, "
                             f"got {tier!r}")
        m = self._members.get(member_id)
        if m is None:
            m = PoolMember(member_id, tier)
            self._members[member_id] = m
        if node_id:
            m.node_id = node_id
        self._refresh_gauges(m)
        return m

    def _set_state(self, m: PoolMember, state: str) -> None:
        if m.state != state:
            m.state = state
            m.state_since = time.monotonic()
        self._refresh_gauges(m)

    def mark_joining(self, member_id: str) -> None:
        m = self._members[member_id]
        self._set_state(m, MemberState.JOINING)

    def mark_healthy(self, member_id: str,
                     node_id: str | None = None) -> None:
        """Member is serving: first join, hot-join, or rejoin after a
        loss — churn in, not a special case."""
        m = self._members[member_id]
        if node_id:
            m.node_id = node_id
        if m.state == MemberState.LOST:
            self.counters["rejoins"] += 1
        elif m.state == MemberState.JOINING:
            self.counters["joins"] += 1
        self._set_state(m, MemberState.HEALTHY)

    def drain(self, member_id: str) -> None:
        """Deliberate drain: excluded from NEW placements immediately;
        whatever is in flight finishes (or is re-placed by on_lost if
        the node dies mid-drain)."""
        m = self._members[member_id]
        if m.state not in (MemberState.DRAINING, MemberState.LOST):
            self.counters["drains"] += 1
            self._m_drains.inc()
            self._set_state(m, MemberState.DRAINING)

    def on_lost(self, member_id: str) -> list[str]:
        """Node death / link loss / leave: capacity is gone NOW. Returns
        the request ids that were in flight there — the caller re-places
        each on a survivor (or sheds structured-retryable when none
        exists). Idempotent: a second loss signal returns []."""
        m = self._members.get(member_id)
        if m is None:
            return []
        if m.state != MemberState.LOST:
            m.losses += 1
            self.counters["losses"] += 1
        self._set_state(m, MemberState.LOST)
        ids = sorted(m.in_flight)
        m.in_flight.clear()
        for req_id in ids:
            if self._assigned.get(req_id) == member_id:
                self._assigned.pop(req_id, None)
            if self._adopted.get(req_id) == member_id:
                self._adopted.pop(req_id, None)
        return ids

    # --------------------------------------------------------- placement

    def _pick(self, tier: str,
              exclude: set[str] | frozenset = frozenset()
              ) -> PoolMember | None:
        live = [m for m in self._members.values()
                if m.tier == tier and m.placeable
                and m.member_id not in exclude]
        if not live:
            return None
        return min(live, key=PoolMember.score)

    def place(self, request_id: str, *,
              exclude: set[str] | frozenset = frozenset()) -> str | None:
        """Least-loaded healthy PREFILL member for one request; None
        when no member is placeable (caller sheds retryable). ASSIGNS
        only — the caller confirms with record_placement() once the
        submit actually reached the member, so a refused send (walked
        past via `exclude` + release()) never inflates the ledger or
        skews the round-robin tie-break."""
        m = self._pick(PREFILL, exclude)
        if m is None:
            return None
        old = self._assigned.get(request_id)
        if old is not None and old != m.member_id:
            prev = self._members.get(old)
            if prev is not None:
                prev.in_flight.discard(request_id)
        self._assigned[request_id] = m.member_id
        m.in_flight.add(request_id)
        self._refresh_gauges(m)
        return m.member_id

    def record_placement(self, request_id: str, *,
                         replacement: bool = False) -> None:
        """The placed submit reached its member: book the placement
        (and the re-placement, when this was churn recovery) in the
        counters, the per-node metric, and the tie-break state."""
        member_id = self._assigned.get(request_id)
        m = self._members.get(member_id) if member_id else None
        if m is None:
            return
        m.placements += 1
        self.counters["placements"] += 1
        self._m_placements.inc(tier=PREFILL, node=m.member_id)
        if replacement:
            self.counters["re_placements"] += 1
            self._m_replacements.inc()

    def route_decode(self, request_id: str) -> str | None:
        """DECODE member for one handed-off request, chosen by the
        queue-depth/burn-rate gauges; releases the prefill assignment
        (the migration left that tier). None when no decode member is
        placeable."""
        self._release_assigned(request_id)
        m = self._pick(DECODE)
        if m is None:
            return None
        self._adopted[request_id] = m.member_id
        m.in_flight.add(request_id)
        m.placements += 1
        self.counters["placements"] += 1
        self._m_placements.inc(tier=DECODE, node=m.member_id)
        self._refresh_gauges(m)
        return m.member_id

    def assigned_to(self, request_id: str) -> str | None:
        return self._assigned.get(request_id)

    def adopted_on(self, request_id: str) -> str | None:
        return self._adopted.get(request_id)

    def release(self, request_id: str) -> None:
        """Undo a placement that never reached the member (send
        failed): the assignment is dropped without counting a loss."""
        self._release_assigned(request_id)

    def _release_assigned(self, request_id: str) -> None:
        member_id = self._assigned.pop(request_id, None)
        if member_id is not None:
            m = self._members.get(member_id)
            if m is not None:
                m.in_flight.discard(request_id)
                self._refresh_gauges(m)

    def note_done(self, request_id: str) -> None:
        """Request ended (any outcome): release whatever it held."""
        self._release_assigned(request_id)
        member_id = self._adopted.pop(request_id, None)
        if member_id is not None:
            m = self._members.get(member_id)
            if m is not None:
                m.in_flight.discard(request_id)
                self._refresh_gauges(m)

    # ---------------------------------------------------------- telemetry

    def update_gauges(self, member_id: str, *,
                      queue_depth: float | None = None,
                      burn_rate: float | None = None) -> None:
        """Feed one member's load gauges (scheduler queue depth off its
        stats probe; SLO burn rate from the provider's monitor) — the
        placement signal beyond the router's own in-flight counts."""
        m = self._members.get(member_id)
        if m is None:
            return
        if queue_depth is not None:
            m.queue_depth = max(float(queue_depth), 0.0)
        if burn_rate is not None:
            m.burn_rate = max(float(burn_rate), 0.0)

    def _refresh_gauges(self, m: PoolMember) -> None:
        self._m_state.set(STATE_CODES[m.state], tier=m.tier,
                          node=m.member_id)
        for tier in (PREFILL, DECODE):
            members = self.members(tier)
            self._m_members.set(len(members), tier=tier)
            self._m_healthy.set(
                sum(1 for x in members if x.placeable), tier=tier)

    # -------------------------------------------------------------- stats

    def healthy_count(self, tier: str) -> int:
        return sum(1 for m in self.members(tier) if m.placeable)

    def stats(self) -> dict[str, Any]:
        return {
            **self.counters,
            "members": {mid: m.to_dict()
                        for mid, m in sorted(self._members.items())},
            "healthy": {PREFILL: self.healthy_count(PREFILL),
                        DECODE: self.healthy_count(DECODE)},
            "in_flight": {PREFILL: len(self._assigned),
                          DECODE: len(self._adopted)},
        }
