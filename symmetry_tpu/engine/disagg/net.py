"""Cross-machine KV-handoff link: the disagg pair over a real network.

PR 7's disaggregated prefill/decode only ever moved handoff frames
across local subprocess pipes on one machine. This module is the
transport that puts the two tiers on separate machines — the link
DistServe and Splitwise treat as the central engineering problem of
disaggregated serving (PAPERS.md): its bandwidth, its flow control, and
its failure behavior all shape the prefill tier's admission rate and the
decode tier's TTFT. It rides the project's injectable transport seam
(transport/base.py): MemoryTransport in tests, TCP in production, and
optionally the same Noise handshake the peer stack uses
(symmetry_tpu.identity) when `tpu.disagg.encrypt` is on.

Topology (static pairing, `tpu.disagg.peer`):

    prefill machine                         decode machine
    ───────────────                         ──────────────
    engine/disagg/node.py                   tpu_native provider
      prefill engine host  ◀── pipe ──┐       decode engine host
      (admissions, chunked prefill)   │       (adoption, generation)
              │ {"op":"handoff"}      │              ▲ {"op":"adopt"}
              ▼                       │              │
      PrefillLink ═══ begin/chunk/end/ack over tcp ══ DecodeLink
                      (this module)

Protocol (LinkOp registry in protocol/keys.py; symlint wire-contract
enforced): each message is a self-delimiting envelope —

    magic b"SYLK" | u32 header-JSON length | u32 payload length |
    header JSON ({"op": ...} + fields) | raw payload bytes

parsed by a STREAMING decoder, so reassembly survives a transport that
fragments or coalesces arbitrarily (the envelope carries its own
boundaries; transport frame boundaries are never load-bearing).

Flow control is credit-based: the decode side advertises a byte window
at hello; every chunk the sender ships consumes credit, every chunk the
decode pump consumes grants it back. Transfers are SERIAL per link and
acked only after the reassembled frame has been written (and drained)
onto the decode host's stdin — so a slow decode tier stops granting
credit/acks, the sender blocks, the prefill node stops reading its
host's stdout, the host's pipe write blocks the engine thread inside the
scheduler's handoff sink, and prefill ADMISSIONS throttle. Bounded
in-flight bytes end to end, no ballooning queue of orphaned KV.

Failure model: a transfer that fails integrity (length/CRC) is nak'd and
retransmitted under a fresh transfer id, up to `max_retries`; an unacked
transfer times out and retransmits the same way; retries exhausted →
`fail`, and the decode node sheds that one request through the existing
structured-retryable path (client failover). A dropped LINK discards
every partial reassembly buffer (the decode tier never adopts a partial
frame — adoption only ever sees length- and CRC-verified complete
frames), sheds every in-flight migration the same retryable way, and
reconnects with exponential backoff. Fault seams: `disagg.net.send`
(per message), `disagg.net.recv` (per message), `disagg.net.drop_link`
(per transfer attempt, after the first chunk — a deterministic
mid-handoff cable pull).

Clock: each connect runs the same NTP-style min-RTT handshake as the
host pipe (utils/trace.clock_handshake_offset), so handoff stamps from
the prefill machine land on the decode machine's clock — the broker's
deadline rebasing and the wire-latency split survive skewed clocks.
"""

from __future__ import annotations

import asyncio
import json
import struct
import time
import uuid
import zlib
from typing import Any, Awaitable, Callable

from symmetry_tpu.protocol.keys import LinkOp
from symmetry_tpu.transport.base import Connection, Transport
from symmetry_tpu.utils.faults import FAULTS
from symmetry_tpu.utils.logging import logger as log
from symmetry_tpu.utils.metrics import METRICS, MetricName

LINK_VERSION = 1
MAGIC = b"SYLK"
_FIXED = struct.Struct("<4sII")

# Envelope bounds: a poisoned length prefix must fail parsing, not drive
# a multi-GB allocation. Chunks are capped well under the TCP framing
# layer's 32 MiB frame bound (protocol/framing.MAX_FRAME_SIZE) — the
# envelope plus Noise overhead must still fit one transport frame.
MAX_HEADER_BYTES = 1 << 20
MAX_PAYLOAD_BYTES = 16 << 20
MAX_CHUNK_BYTES = 8 << 20

DEFAULT_CHUNK_BYTES = 1 << 20
DEFAULT_CREDIT_BYTES = 64 << 20
# Reassembly bounds (decode side): one transfer may not claim more than
# the host pipe's own handoff line limit, and a sender is SERIAL by
# protocol, so more than a couple of live transfers is a protocol
# violation — both caps keep a rogue or corrupted peer from growing
# decode-side buffers without limit on an unencrypted listener.
MAX_TRANSFER_BYTES = 1 << 30
MAX_ACTIVE_TRANSFERS = 2
DEFAULT_ACK_TIMEOUT_S = 30.0
DEFAULT_MAX_RETRIES = 2
DEFAULT_RECONNECT_BASE_S = 0.5
DEFAULT_RECONNECT_MAX_S = 15.0
CLOCK_ROUNDS = 5


class LinkError(ConnectionError):
    """The handoff link failed (protocol violation, drop, or teardown)."""


# ------------------------------------------------------------- envelope


def encode_link_msg(header: dict[str, Any], payload: bytes = b"") -> bytes:
    """One link message → self-delimiting bytes (see module docstring)."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    if len(hdr) > MAX_HEADER_BYTES:
        raise LinkError(f"link header too large: {len(hdr)} bytes")
    if len(payload) > MAX_PAYLOAD_BYTES:
        raise LinkError(f"link payload too large: {len(payload)} bytes")
    return b"".join([_FIXED.pack(MAGIC, len(hdr), len(payload)), hdr,
                     payload])


class LinkDecoder:
    """Streaming envelope parser: feed arbitrary byte blobs, iterate
    complete (header, payload) messages. Boundary-agnostic on purpose —
    the reassembly contract must hold over a transport that fragments
    and coalesces however it likes."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes):
        self._buf.extend(data)
        while True:
            if len(self._buf) < _FIXED.size:
                return
            magic, hlen, plen = _FIXED.unpack_from(self._buf)
            if magic != MAGIC:
                raise LinkError(f"bad link magic {bytes(magic)!r}")
            if hlen > MAX_HEADER_BYTES or plen > MAX_PAYLOAD_BYTES:
                raise LinkError(
                    f"link message too large (header {hlen}, "
                    f"payload {plen})")
            total = _FIXED.size + hlen + plen
            if len(self._buf) < total:
                return
            try:
                header = json.loads(
                    bytes(self._buf[_FIXED.size:_FIXED.size + hlen]))
            except ValueError as exc:
                raise LinkError(f"link header is not JSON: {exc}") from exc
            if not isinstance(header, dict):
                raise LinkError("link header must be a JSON object")
            payload = bytes(self._buf[_FIXED.size + hlen:total])
            del self._buf[:total]
            yield header, payload


# ------------------------------------------------------------ link layer


class HandoffLink:
    """One live link: envelope codec + optional Noise encryption + the
    send/recv fault seams, over a frame Connection."""

    def __init__(self, conn: Connection, session: Any = None) -> None:
        self._conn = conn
        self._session = session  # identity.SecureSession or None
        self._decoder = LinkDecoder()
        self._pending: list[tuple[dict, bytes]] = []
        self.stats = {"msgs_sent": 0, "msgs_recvd": 0,
                      "bytes_sent": 0, "bytes_recvd": 0}

    @property
    def closed(self) -> bool:
        return self._conn.closed

    @property
    def remote_address(self) -> str:
        return self._conn.remote_address

    async def send(self, header: dict[str, Any],
                   payload: bytes = b"") -> None:
        if FAULTS.enabled and await FAULTS.apoint("disagg.net.send"):
            return  # injected drop: the message is lost on the wire
        data = encode_link_msg(header, payload)
        if self._session is not None:
            data = self._session.encrypt(data)
        self.stats["msgs_sent"] += 1
        self.stats["bytes_sent"] += len(data)
        try:
            await self._conn.send(data)
        except (ConnectionError, OSError) as exc:
            raise LinkError(f"link send failed: {exc}") from exc

    async def recv(self) -> tuple[dict[str, Any], bytes] | None:
        """Next decoded message, or None on EOF/teardown. Protocol
        violations raise LinkError — the caller drops the link (a
        corrupted stream cannot be resynchronized; reconnect instead)."""
        while True:
            if self._pending:
                header, payload = self._pending.pop(0)
                if (FAULTS.enabled
                        and await FAULTS.apoint("disagg.net.recv")):
                    continue  # injected drop: message vanishes on ingress
                return header, payload
            try:
                frame = await self._conn.recv()
            except (ConnectionError, OSError):
                return None
            if frame is None:
                return None
            if self._session is not None:
                try:
                    frame = self._session.decrypt(frame)
                except Exception as exc:
                    raise LinkError(f"link decrypt failed: {exc}") from exc
            self.stats["msgs_recvd"] += 1
            self.stats["bytes_recvd"] += len(frame)
            self._pending.extend(self._decoder.feed(frame))

    def requeue(self, msgs: list[tuple[dict[str, Any], bytes]]) -> None:
        """Put already-received messages back at the FRONT of the inbox
        (arrival order preserved) — used by the clock handshake, which
        reads inline before the pump exists and must not discard
        unrelated traffic the peer sent concurrently."""
        self._pending[:0] = msgs

    async def drop(self, reason: str = "") -> None:
        """Hard-cut the link (fault injection / protocol violation)."""
        if reason:
            log.warning(f"handoff link dropped: {reason}")
        await self._conn.close()

    async def close(self) -> None:
        await self._conn.close()


async def secure_link(conn: Connection, cfg: "LinkConfig",
                      *, initiator: bool) -> HandoffLink:
    """Wrap a fresh connection: run the Noise handshake when the link is
    configured encrypted (requires the `cryptography` dependency),
    otherwise plaintext envelopes."""
    session = None
    if cfg.encrypt:
        from symmetry_tpu.identity import (
            Identity,
            client_handshake,
            server_handshake,
        )

        ident = Identity.from_name(cfg.secret or "disagg-link")
        expected = bytes.fromhex(cfg.peer_key) if cfg.peer_key else None
        hs = client_handshake if initiator else server_handshake
        try:
            session = await hs(conn, ident, expected)
        except Exception:
            await conn.close()
            raise
    return HandoffLink(conn, session)


# ---------------------------------------------------------------- config


class LinkConfig:
    """The `tpu.disagg` link settings (all optional; `peer` on the
    decode/provider side or `listen` on the prefill-node side selects
    network mode)."""

    def __init__(self, disagg: dict[str, Any] | None) -> None:
        d = disagg or {}
        self._raw: dict[str, Any] = dict(d)
        self.peer: str | None = d.get("peer")
        self.listen: str | None = d.get("listen")
        # Stable per-link identity announced in the hello (pool
        # membership); defaults to the bound/dialed address when unset.
        self.node_id: str | None = d.get("node_id")
        # Link keepalive (pool mode): the decode side pings every
        # heartbeat_s and DROPS a link silent for ~2 periods — a wedged
        # peer becomes a membership-churn event instead of a hang. 0
        # (the pair default) disables it.
        self.heartbeat_s: float = float(d.get("heartbeat_s", 0.0))
        # inline: the backend self-hosts the PrefillNode in-process and
        # dials it at `peer` — the full wire path (chunking, credit,
        # acks, reconnect) in one provider process. Benches, smokes, and
        # tests run this; production runs the node on its own machine.
        self.inline: bool = bool(d.get("inline", False))
        # Clamped to [4 KiB, MAX_CHUNK_BYTES]: chunk_kb 0 would make the
        # sender's range() step zero, and a chunk over the cap would not
        # fit one TCP-layer frame.
        self.chunk_bytes: int = min(max(
            int(d.get("chunk_kb", DEFAULT_CHUNK_BYTES // 1024)) * 1024,
            4096), MAX_CHUNK_BYTES)
        self.credit_bytes: int = max(int(
            float(d.get("credit_mb",
                        DEFAULT_CREDIT_BYTES / 2**20)) * 2**20),
            self.chunk_bytes)
        self.ack_timeout_s: float = float(
            d.get("ack_timeout_s", DEFAULT_ACK_TIMEOUT_S))
        self.max_retries: int = int(d.get("max_retries",
                                          DEFAULT_MAX_RETRIES))
        self.reconnect_base_s: float = float(
            d.get("reconnect_base_s", DEFAULT_RECONNECT_BASE_S))
        self.reconnect_max_s: float = float(
            d.get("reconnect_max_s", DEFAULT_RECONNECT_MAX_S))
        self.encrypt: bool = bool(d.get("encrypt", False))
        self.secret: str | None = d.get("secret")
        self.peer_key: str | None = d.get("peer_key")

    @property
    def network_mode(self) -> bool:
        return self.peer is not None

    def for_peer(self, peer: str, **overrides: Any) -> "LinkConfig":
        """A member-link config: this config with `peer` (and any
        per-member overrides, e.g. heartbeat_s) replaced — how the pool
        derives M per-member links from one `tpu.disagg` mapping."""
        return LinkConfig({**self._raw, "peer": peer, **overrides})


_MEM_HUB = None


def link_transport(address: str) -> Transport:
    """Transport by link-address scheme. `mem://` resolves against ONE
    process-global hub so an inline node and the backend (or a test's
    two endpoints) find each other without plumbing a hub instance."""
    if address.startswith("mem://"):
        global _MEM_HUB
        if _MEM_HUB is None:
            from symmetry_tpu.transport.memory import MemoryTransport

            _MEM_HUB = MemoryTransport()
        return _MEM_HUB
    if address.startswith("tcp://"):
        from symmetry_tpu.transport.tcp import TcpTransport

        return TcpTransport()
    raise ValueError(f"unsupported link address {address!r} "
                     f"(want tcp:// or mem://)")


# ----------------------------------------------------------- flow control


class CreditGate:
    """Sender-side byte window. `acquire(n)` blocks while the window is
    exhausted (that stall IS the cross-machine backpressure — it
    propagates through the node's serial pump into the prefill host's
    stdout pipe and from there into the scheduler's handoff sink);
    `grant(n)` returns consumed bytes from the receiver's credit
    messages."""

    def __init__(self, window: int) -> None:
        self._credit = window
        self._waiter: asyncio.Future | None = None
        self.stats = {"credit_stalls": 0, "credit_stall_s": 0.0}
        self._m_stalls = METRICS.counter(
            MetricName.LINK_CREDIT_STALLS,
            "sender stalls on an exhausted credit window")

    @property
    def available(self) -> int:
        return self._credit

    def grant(self, n: int) -> None:
        self._credit += n
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)

    def reset(self, window: int) -> None:
        """Resync to a known in-flight-zero point. Transfers are serial
        and always end in ack/nak/timeout, so at each transfer-attempt
        START no legitimate chunk bytes are outstanding — any credit
        deficit at that moment is LEAKED window (a chunk dropped by the
        wire or a fault seam consumed credit the receiver never saw and
        can never grant back). Without this, lossy-seam chaos drills
        shrink the window monotonically until acquire() wedges forever."""
        self._credit = window
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)

    async def acquire(self, n: int) -> None:
        stalled_at = None
        while self._credit < n:
            if stalled_at is None:
                stalled_at = time.monotonic()
                self.stats["credit_stalls"] += 1
                self._m_stalls.inc()
            self._waiter = asyncio.get_running_loop().create_future()
            await self._waiter
        if stalled_at is not None:
            self.stats["credit_stall_s"] += time.monotonic() - stalled_at
        self._credit -= n


# ------------------------------------------------------------- reassembly


class Reassembler:
    """Decode-side chunk reassembly, keyed by transfer id so a
    retransmit under a fresh id can never interleave with a stale
    attempt's chunks. Completion hands back length-checked bytes whose
    CRC the `end` header pins — a partial or corrupt transfer raises
    and is discarded; nothing partial ever leaves this class."""

    def __init__(self) -> None:
        self._bufs: dict[str, dict[str, Any]] = {}
        self.stats = {"partial_discards": 0, "stale_chunks": 0}
        self._m_partials = METRICS.counter(
            MetricName.LINK_PARTIAL_DISCARDS,
            "partial/corrupt transfers discarded (never adopted)")

    def _discard(self, n: int = 1) -> None:
        self.stats["partial_discards"] += n
        self._m_partials.inc(n)

    @property
    def active(self) -> int:
        return len(self._bufs)

    def begin(self, header: dict[str, Any]) -> None:
        xfer = str(header.get("xfer", ""))
        total = int(header.get("len", -1))
        if not xfer or total < 0:
            raise LinkError(f"malformed begin header: {header}")
        if total > MAX_TRANSFER_BYTES:
            raise LinkError(f"transfer claims {total} bytes, over the "
                            f"{MAX_TRANSFER_BYTES}-byte bound")
        if len(self._bufs) >= MAX_ACTIVE_TRANSFERS:
            # Senders are serial; piling up transfers is a protocol
            # violation. Evict the oldest — its sender retries or fails.
            stale = next(iter(self._bufs))
            self._bufs.pop(stale)
            self._discard()
        self._bufs[xfer] = {"buf": bytearray(), "total": total,
                            "next_seq": 0, "meta": header}

    def chunk(self, header: dict[str, Any], payload: bytes) -> bool:
        """Append one chunk; False when the transfer is unknown/stale
        (late chunks of an aborted attempt — credit is still granted by
        the caller so abandoned bytes never leak window)."""
        entry = self._bufs.get(str(header.get("xfer", "")))
        if entry is None:
            self.stats["stale_chunks"] += 1
            return False
        if int(header.get("seq", -1)) != entry["next_seq"]:
            # Out-of-order over an ordered transport = protocol bug or
            # corruption; kill the attempt, let the retry fix it.
            self._bufs.pop(str(header.get("xfer", "")), None)
            self._discard()
            raise LinkError(
                f"chunk seq {header.get('seq')} != expected "
                f"{entry['next_seq']}")
        entry["next_seq"] += 1
        entry["buf"] += payload
        if len(entry["buf"]) > entry["total"]:
            self._bufs.pop(str(header.get("xfer", "")), None)
            self._discard()
            raise LinkError("transfer overflow: more chunk bytes than "
                            "the begin header promised")
        return True

    def end(self, header: dict[str, Any]) -> tuple[dict, bytes]:
        """Complete a transfer → (begin meta, verified frame bytes).
        Raises LinkError on any mismatch (caller naks; sender retries)."""
        xfer = str(header.get("xfer", ""))
        entry = self._bufs.pop(xfer, None)
        if entry is None:
            raise LinkError(f"end for unknown transfer {xfer!r}")
        buf = bytes(entry["buf"])
        if len(buf) != entry["total"]:
            self._discard()
            raise LinkError(f"transfer truncated: {len(buf)} of "
                            f"{entry['total']} bytes")
        crc = int(header.get("crc", -1))
        if zlib.crc32(buf) != crc:
            self._discard()
            raise LinkError("transfer checksum mismatch")
        return entry["meta"], buf

    def abort_all(self) -> int:
        """Link died: discard every partial buffer. Returns the count —
        each was a handoff mid-flight whose request the caller sheds."""
        n = len(self._bufs)
        self._discard(n)
        self._bufs.clear()
        return n


# ----------------------------------------------------------------- sender


class HandoffSender:
    """Prefill-node side of the bulk path: one serial, credit-gated,
    acked transfer per handoff frame (see module docstring for why
    serial = the backpressure contract)."""

    def __init__(self, link: HandoffLink, gate: CreditGate,
                 cfg: LinkConfig, window: int | None = None) -> None:
        self._link = link
        self._gate = gate
        self._cfg = cfg
        self._window = window if window is not None else cfg.credit_bytes
        # (xfer) -> future resolved True by ack, False by nak
        self._acks: dict[str, asyncio.Future] = {}
        self.stats = {"handoffs_sent": 0, "handoff_bytes_sent": 0,
                      "retries": 0, "failed": 0}
        self._m_retries = METRICS.counter(
            MetricName.LINK_RETRIES,
            "handoff transfer retransmissions performed")

    def on_ack(self, header: dict[str, Any], ok: bool) -> None:
        fut = self._acks.get(str(header.get("xfer", "")))
        if fut is not None and not fut.done():
            fut.set_result(ok)

    def fail_all(self) -> None:
        """Link died: every in-flight ack wait resolves as failed."""
        for fut in self._acks.values():
            if not fut.done():
                fut.set_result(False)

    async def send_handoff(self, meta: dict[str, Any],
                           frame: bytes) -> bool:
        """Ship one frame; True once the decode node acked full
        reassembly + forwarding. False = retries exhausted or the link
        died mid-transfer (the decode side sheds the request; a best-
        effort `fail` tells it not to wait for the ack timeout)."""
        req_id = str(meta.get("id", ""))
        for attempt in range(1, self._cfg.max_retries + 2):
            xfer = uuid.uuid4().hex[:12]
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            self._acks[xfer] = fut
            try:
                ok = await self._attempt(meta, frame, xfer, attempt, fut)
            except LinkError:
                self._acks.pop(xfer, None)
                self.stats["failed"] += 1
                return False  # link is gone; reconnect path owns recovery
            finally:
                self._acks.pop(xfer, None)
            if ok:
                self.stats["handoffs_sent"] += 1
                self.stats["handoff_bytes_sent"] += len(frame)
                return True
            retrying = attempt <= self._cfg.max_retries
            if retrying:
                # retries counts RETRANSMISSIONS actually performed —
                # the stat the bench reads as wasted wire work.
                self.stats["retries"] += 1
                self._m_retries.inc()
            log.warning(f"handoff {req_id} attempt {attempt} "
                        f"unacked/nak'd; "
                        f"{'retrying' if retrying else 'giving up'}")
        self.stats["failed"] += 1
        try:
            await self._link.send({"op": LinkOp.FAIL, "id": req_id,
                                   "reason": "handoff retries exhausted"})
        except LinkError:
            pass
        return False

    async def _attempt(self, meta: dict[str, Any], frame: bytes,
                       xfer: str, attempt: int,
                       fut: asyncio.Future) -> bool:
        # Transfer boundary = in-flight zero: clamp any credit leaked
        # by dropped chunks (see CreditGate.reset).
        self._gate.reset(self._window)
        begin = {**meta, "op": LinkOp.BEGIN, "xfer": xfer,
                 "len": len(frame), "attempt": attempt,
                 "t": time.monotonic()}
        await self._link.send(begin)
        step = self._cfg.chunk_bytes
        for seq, off in enumerate(range(0, len(frame), step)):
            if fut.done():
                # Early nak (the receiver killed this attempt on a seq/
                # overflow error): stop burning wire on a dead transfer.
                # (Already resolved — the await returns immediately.)
                return bool(await fut)
            try:
                # Bounded: ack_timeout_s only arms after END, so a
                # credit stall from leaked window (lossy seams dropping
                # CHUNK/CREDIT messages) would otherwise wedge HERE
                # forever — time it out into a failed attempt; the next
                # attempt's gate reset reclaims the leaked window.
                await asyncio.wait_for(
                    self._gate.acquire(min(step, len(frame) - off)),
                    self._cfg.ack_timeout_s)
            except asyncio.TimeoutError:
                return False
            await self._link.send(
                {"op": LinkOp.CHUNK, "xfer": xfer, "seq": seq},
                frame[off:off + step])
            if seq == 0 and FAULTS.enabled \
                    and FAULTS.point("disagg.net.drop_link"):
                # Deterministic mid-handoff cable pull: begin + one
                # chunk are on the wire, the rest never arrives. One
                # hit per transfer attempt, so @nth=N targets the Nth
                # handoff attempt exactly.
                await self._link.drop("injected drop_link fault")
                raise LinkError("link dropped by fault injection")
        await self._link.send({"op": LinkOp.END, "xfer": xfer,
                               "crc": zlib.crc32(frame)})
        try:
            return bool(await asyncio.wait_for(
                fut, self._cfg.ack_timeout_s))
        except asyncio.TimeoutError:
            return False


# -------------------------------------------------------- clock handshake


async def link_clock_handshake(link: HandoffLink,
                               rounds: int = CLOCK_ROUNDS) -> float:
    """Measure the peer's monotonic-clock offset over the link (dialer
    side, before the pump starts — replies are read inline). Same
    min-RTT NTP-midpoint estimate as the host pipe handshake; returns
    `offset = peer_clock - local_clock`."""
    from symmetry_tpu.utils.trace import clock_handshake_offset

    samples: list[tuple[float, float, float]] = []
    deferred: list[tuple[dict[str, Any], bytes]] = []
    try:
        for _ in range(rounds):
            t0 = time.monotonic()
            await link.send({"op": LinkOp.CLOCK, "t0": t0})
            while True:
                msg = await link.recv()
                if msg is None:
                    raise LinkError("link died during clock handshake")
                header, payload = msg
                if header.get("op") == LinkOp.CLOCK \
                        and header.get("t0") == t0:
                    samples.append((t0, float(header["t"]),
                                    time.monotonic()))
                    break
                # The peer's side of the link is live before our rounds
                # finish (the node serves the moment it replies hello):
                # an event/fail/begin arriving here belongs to the pump
                # — defer it, never discard it.
                deferred.append((header, payload))
    finally:
        if deferred:
            link.requeue(deferred)
    return clock_handshake_offset(samples)


# ------------------------------------------------------------ decode side


class DecodeLink:
    """The decode/provider node's end: dial `tpu.disagg.peer`, keep the
    link alive with exponential-backoff reconnects, pump inbound
    messages, reassemble handoff transfers, and ack only after the
    frame has been handed to the decode host.

    Callbacks (all run on the owner's event loop):
      on_handoff(meta, frame)  awaited with the begin meta + verified
                               frame bytes; raising → nak (sender
                               retries); returning → ack
      on_event(ev)             a prefill-tier terminal event dict
      on_fail(req_id, reason)  handoff abandoned by the sender
      on_down(reason)          the link just died; in-flight migrations
                               must shed (reconnect is automatic)
      on_up()                  link (re)connected and clock-synced
      on_drain(node)           peer announced a deliberate drain: stop
                               NEW placements; in-flight work finishes
      on_leave(node)           peer announced departure (membership
                               churn, not a fault)
    """

    def __init__(self, cfg: LinkConfig, *,
                 on_handoff: Callable[[dict, bytes], Awaitable[None]],
                 on_event: Callable[[dict], None],
                 on_fail: Callable[[str, str], None],
                 on_down: Callable[[str], None],
                 on_up: Callable[[], None] | None = None,
                 on_drain: Callable[[str], None] | None = None,
                 on_leave: Callable[[str], None] | None = None) -> None:
        self.cfg = cfg
        self._on_handoff = on_handoff
        self._on_event = on_event
        self._on_fail = on_fail
        self._on_down = on_down
        self._on_up = on_up
        self._on_drain = on_drain
        self._on_leave = on_leave
        # Peer-announced identity off the hello ("node") — the pool
        # router's member naming; falls back to the dialed address.
        self.peer_node: str | None = None
        self._last_rx = 0.0
        self._hb_task: asyncio.Task | None = None
        self._transport = link_transport(cfg.peer)
        self._link: HandoffLink | None = None
        self._reasm = Reassembler()
        self._task: asyncio.Task | None = None
        self._connected = asyncio.Event()
        self._stopped = False
        self.clock_offset = 0.0
        # (op) -> waiters for stats/trace probe replies over the link
        self._waiters: dict[str, list[asyncio.Future]] = {
            LinkOp.STATS: [], LinkOp.TRACE: []}
        self.stats = {"connects": 0, "drops": 0, "wire_frames": 0,
                      "wire_bytes": 0}
        self._m_connects = METRICS.counter(
            MetricName.LINK_CONNECTS, "handoff link connects")
        self._m_drops = METRICS.counter(
            MetricName.LINK_DROPS, "handoff link drops")
        # Peer-labeled: a pool runs one DecodeLink per member, and an
        # unlabeled gauge would be clobbered by whichever link moved
        # last. The pair gets one series; symtop sums across peers.
        self._m_connected = METRICS.gauge(
            MetricName.LINK_CONNECTED, "handoff link up (1) / down (0)",
            labels=("peer",))
        self._m_wire_frames = METRICS.counter(
            MetricName.LINK_WIRE_FRAMES,
            "complete handoff frames received off the link")
        self._m_wire_bytes = METRICS.counter(
            MetricName.LINK_WIRE_BYTES,
            "handoff frame bytes received off the link")

    # -------------------------------------------------------- lifecycle

    async def start(self, *, wait_s: float | None = None) -> None:
        """Begin the connect/pump loop; optionally block until the
        first successful connect (startup wants the link proven)."""
        self._task = asyncio.get_running_loop().create_task(
            self._run())
        if wait_s is not None:
            try:
                await asyncio.wait_for(self._connected.wait(), wait_s)
            except asyncio.TimeoutError:
                raise LinkError(
                    f"handoff link to {self.cfg.peer} not up within "
                    f"{wait_s:.0f}s") from None

    async def stop(self) -> None:
        import contextlib

        self._stopped = True
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        if self._link is not None:
            await self._link.close()
            self._link = None

    @property
    def connected(self) -> bool:
        return (self._connected.is_set() and self._link is not None
                and not self._link.closed)

    @property
    def reassembly_stats(self) -> dict[str, int]:
        return dict(self._reasm.stats)

    # ------------------------------------------------------------- sends

    async def _send(self, header: dict[str, Any],
                    payload: bytes = b"") -> None:
        link = self._link
        if link is None or not self._connected.is_set():
            raise LinkError("handoff link down")
        await link.send(header, payload)

    async def submit(self, op: dict[str, Any]) -> None:
        """Forward one host submit op to the prefill node (payload =
        the JSON line the node splices onto its host's stdin)."""
        await self._send({"op": LinkOp.SUBMIT},
                         json.dumps(op, separators=(",", ":")).encode())

    async def cancel(self, op: dict[str, Any]) -> None:
        await self._send({"op": LinkOp.CANCEL},
                         json.dumps(op, separators=(",", ":")).encode())

    async def probe(self, op: str, timeout: float = 10.0) -> dict | None:
        """stats/trace round-trip over the link; None on timeout or a
        down link (mirrors the backend's host-pipe probes)."""
        if op not in self._waiters:
            raise ValueError(f"unknown link probe {op!r}")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters[op].append(fut)
        # Spelled as two literal headers (not {"op": op}) so the symlint
        # wire-contract checker sees the producer side of both ops.
        header = ({"op": LinkOp.STATS} if op == LinkOp.STATS
                  else {"op": LinkOp.TRACE})
        try:
            try:
                await self._send(header)
            except LinkError:
                return None
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            if fut in self._waiters[op]:
                self._waiters[op].remove(fut)

    # -------------------------------------------------------------- pump

    async def _run(self) -> None:
        backoff = self.cfg.reconnect_base_s
        while not self._stopped:
            try:
                conn = await self._transport.dial(self.cfg.peer)
                link = await secure_link(conn, self.cfg, initiator=True)
            except Exception as exc:  # noqa: BLE001 — any dial failure
                log.warning(f"handoff link dial {self.cfg.peer} failed: "
                            f"{exc}; retrying in {backoff:.1f}s")
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.cfg.reconnect_max_s)
                continue
            try:
                await link.send({"op": LinkOp.HELLO,
                                 "version": LINK_VERSION,
                                 "role": "decode",
                                 "node": self.cfg.node_id or "",
                                 "window": self.cfg.credit_bytes})
                msg = await link.recv()
                if msg is None or msg[0].get("op") != LinkOp.HELLO:
                    raise LinkError("no hello from prefill node")
                if int(msg[0].get("version", 0)) != LINK_VERSION:
                    raise LinkError(
                        f"link version mismatch: peer speaks "
                        f"{msg[0].get('version')}, this build "
                        f"{LINK_VERSION}")
                self.peer_node = (str(msg[0].get("node") or "")
                                  or self.cfg.peer)
                self.clock_offset = await link_clock_handshake(link)
            except Exception as exc:  # noqa: BLE001 — handshake failure
                await link.close()
                log.warning(f"handoff link handshake failed: {exc}; "
                            f"retrying in {backoff:.1f}s")
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, self.cfg.reconnect_max_s)
                continue
            backoff = self.cfg.reconnect_base_s
            self._link = link
            self._connected.set()
            self.stats["connects"] += 1
            self._m_connects.inc()
            self._m_connected.set(1, peer=self.cfg.peer or "")
            log.info(f"handoff link up: {link.remote_address} "
                     f"clock_offset={self.clock_offset * 1e6:+.0f}us")
            if self._on_up is not None:
                self._on_up()
            self._last_rx = time.monotonic()
            if self.cfg.heartbeat_s > 0:
                self._hb_task = asyncio.get_running_loop().create_task(
                    self._heartbeat(link))
            try:
                reason = await self._pump(link)
            except Exception as exc:  # noqa: BLE001 — a malformed header
                # (non-numeric len/seq/crc…) must drop the LINK and
                # reconnect, never silently kill this task while
                # _connected stays set and every stream hangs.
                reason = f"link pump error: {exc!r}"
            if self._hb_task is not None:
                self._hb_task.cancel()
                self._hb_task = None
            self._connected.clear()
            self._link = None
            self.stats["drops"] += 1
            self._m_drops.inc()
            self._m_connected.set(0, peer=self.cfg.peer or "")
            shed = self._reasm.abort_all()
            for lst in self._waiters.values():
                for fut in lst:
                    if not fut.done():
                        fut.set_result(None)
                lst.clear()
            await link.close()
            if self._stopped:
                return
            log.warning(f"handoff link down ({reason}); {shed} partial "
                        f"transfer(s) discarded; reconnecting")
            self._on_down(reason)

    async def _heartbeat(self, link: HandoffLink) -> None:
        """Keepalive pings (pool mode). ANY inbound traffic counts as
        liveness (_last_rx is stamped by the pump); a link silent for
        ~2 periods is cut here — the pump sees the close, the down path
        sheds, and the reconnect loop owns recovery. A wedged-but-
        connected peer thus becomes ordinary membership churn."""
        period = self.cfg.heartbeat_s
        while not link.closed:
            await asyncio.sleep(period)
            silent = time.monotonic() - self._last_rx
            if silent > 2 * period:
                await link.drop(
                    f"keepalive: no traffic for {silent:.1f}s")
                return
            try:
                await link.send({"op": LinkOp.PING,
                                 "t": time.monotonic()})
            except LinkError:
                return  # pump is already tearing the link down

    async def _pump(self, link: HandoffLink) -> str:
        while True:
            try:
                msg = await link.recv()
            except LinkError as exc:
                return str(exc)
            if msg is None:
                return "link EOF"
            self._last_rx = time.monotonic()
            header, payload = msg
            op = header.get("op")
            try:
                if op == LinkOp.CHUNK:
                    # Credit returns whether the chunk lands, is stale,
                    # or fails integrity — abandoned attempts must not
                    # leak window.
                    await link.send({"op": LinkOp.CREDIT,
                                     "n": len(payload)})
                    self._reasm.chunk(header, payload)
                elif op == LinkOp.BEGIN:
                    self._reasm.begin(header)
                elif op == LinkOp.END:
                    await self._complete(link, header)
                elif op == LinkOp.EVENT:
                    ev = _json_payload(payload)
                    if ev is not None:
                        self._on_event(ev)
                elif op == LinkOp.FAIL:
                    self._on_fail(str(header.get("id", "")),
                                  str(header.get("reason", "")))
                elif op in (LinkOp.STATS, LinkOp.TRACE):
                    reply = _json_payload(payload)
                    waiters, self._waiters[op] = self._waiters[op], []
                    for fut in waiters:
                        if not fut.done():
                            fut.set_result(reply)
                elif op == LinkOp.DRAIN:
                    if self._on_drain is not None:
                        self._on_drain(str(header.get("node", "")))
                elif op == LinkOp.LEAVE:
                    if self._on_leave is not None:
                        self._on_leave(str(header.get("node", "")))
                elif op == LinkOp.PONG:
                    pass  # liveness already stamped by _last_rx above
                elif op == LinkOp.CLOCK:
                    # Stray post-handshake probe echo; ignore.
                    pass
                elif op == LinkOp.HELLO:
                    pass
                else:
                    return f"unknown link op {op!r}"
            except LinkError as exc:
                # Reassembly integrity failure: nak THIS transfer (the
                # sender retries under a fresh id); the link survives.
                xfer = str(header.get("xfer", ""))
                log.warning(f"handoff transfer {xfer} failed: {exc}")
                try:
                    await link.send({"op": LinkOp.NAK, "xfer": xfer,
                                     "reason": str(exc)})
                except LinkError as exc2:
                    return str(exc2)

    async def _complete(self, link: HandoffLink,
                        header: dict[str, Any]) -> None:
        meta, frame = self._reasm.end(header)  # raises LinkError → nak
        t_emit = meta.get("t")
        if t_emit is not None:
            # The wire leg on THIS machine's clock: sender stamp mapped
            # through the measured link offset. Sub-RTT jitter can make
            # it microsecond-negative; clamp for the histogram.
            wire_s = max(
                time.monotonic() - (float(t_emit) - self.clock_offset),
                0.0)
            meta = {**meta, "wire_s": wire_s}
        self.stats["wire_frames"] += 1
        self.stats["wire_bytes"] += len(frame)
        self._m_wire_frames.inc()
        self._m_wire_bytes.inc(len(frame))
        xfer = str(header.get("xfer", ""))
        try:
            await self._on_handoff(meta, frame)
        except Exception as exc:  # noqa: BLE001 — adoption-side failure
            raise LinkError(f"handoff forward failed: {exc}") from exc
        await link.send({"op": LinkOp.ACK, "xfer": xfer})


def _json_payload(payload: bytes) -> dict | None:
    try:
        obj = json.loads(payload)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) else None


# ----------------------------------------------------------- prefill side


class PrefillLink:
    """The prefill node's end of ONE accepted connection: hello reply,
    clock echoes, command forwarding, and the sender-side bulk path.
    The node accepts one live link at a time (a reconnect replaces the
    old connection); `serve()` returns on EOF.

    Callbacks:
      on_command(line: bytes)      awaited with one host-pipe JSON line
                                   (submit/cancel) to splice onto the
                                   prefill host's stdin
      on_probe(op) -> dict|None    awaited for stats/trace probes;
                                   the reply rides back over the link
    """

    def __init__(self, link: HandoffLink, cfg: LinkConfig, *,
                 on_command: Callable[[bytes], Awaitable[None]],
                 on_probe: Callable[[str], Awaitable[dict | None]],
                 node_id: str | None = None) -> None:
        self._link = link
        self._cfg = cfg
        self._on_command = on_command
        self._on_probe = on_probe
        # Identity announced in the hello reply — the pool router's
        # member naming for this node.
        self.node_id = node_id or cfg.node_id or ""
        self.peer_node: str | None = None  # dialer's announced identity
        # Window starts at the peer's advertised hello value; replaced
        # in handshake().
        self._gate = CreditGate(cfg.credit_bytes)
        self.sender = HandoffSender(link, self._gate, cfg)
        # Probe replies run OFF the pump (strong refs — the loop holds
        # tasks weakly): a stats round-trip to the host can take
        # seconds, and awaiting it inline would stop CREDIT/ACK
        # processing — deadlocking against the node's host pump, which
        # may itself be blocked inside send_handoff waiting for those
        # very grants while it alone can read the host's stats reply.
        self._probe_tasks: set[asyncio.Task] = set()

    @property
    def closed(self) -> bool:
        return self._link.closed

    async def handshake(self, timeout: float = 30.0) -> None:
        """Expect the dialer's hello; reply with ours. The dialer's
        advertised window seeds the credit gate."""
        async def _hello() -> None:
            msg = await self._link.recv()
            if msg is None or msg[0].get("op") != LinkOp.HELLO:
                raise LinkError("dialer sent no hello")
            if int(msg[0].get("version", 0)) != LINK_VERSION:
                raise LinkError(
                    f"link version mismatch: peer speaks "
                    f"{msg[0].get('version')}, this build {LINK_VERSION}")
            window = int(msg[0].get("window", self._cfg.credit_bytes))
            self.peer_node = str(msg[0].get("node") or "") or None
            self._gate = CreditGate(window)
            self.sender = HandoffSender(self._link, self._gate,
                                        self._cfg, window=window)
            await self._link.send({"op": LinkOp.HELLO,
                                   "version": LINK_VERSION,
                                   "role": "prefill",
                                   "node": self.node_id,
                                   "window": window})

        await asyncio.wait_for(_hello(), timeout)

    async def send_handoff(self, meta: dict[str, Any],
                           frame: bytes) -> bool:
        return await self.sender.send_handoff(meta, frame)

    async def send_event(self, ev: dict[str, Any]) -> None:
        await self._link.send(
            {"op": LinkOp.EVENT},
            json.dumps(ev, separators=(",", ":")).encode())

    async def send_drain(self) -> None:
        """Announce a deliberate drain: the decode side's pool router
        stops placing NEW work here; in-flight requests finish."""
        await self._link.send({"op": LinkOp.DRAIN, "node": self.node_id})

    async def send_leave(self) -> None:
        """Announce departure (drain complete / shutdown): membership
        churn the router accounts, not a fault it recovers from."""
        await self._link.send({"op": LinkOp.LEAVE, "node": self.node_id})

    async def serve(self) -> str:
        """Inbound pump until the link dies; returns the reason."""
        link = self._link
        while True:
            try:
                msg = await link.recv()
            except LinkError as exc:
                return str(exc)
            if msg is None:
                return "link EOF"
            header, payload = msg
            op = header.get("op")
            if op == LinkOp.CREDIT:
                self._gate.grant(int(header.get("n", 0)))
            elif op == LinkOp.ACK:
                self.sender.on_ack(header, True)
            elif op == LinkOp.NAK:
                self.sender.on_ack(header, False)
            elif op in (LinkOp.SUBMIT, LinkOp.CANCEL):
                try:
                    await self._on_command(payload)
                except Exception as exc:  # noqa: BLE001 — host pipe down
                    log.warning(f"link command forward failed: {exc}")
            elif op == LinkOp.CLOCK:
                try:
                    await link.send({"op": LinkOp.CLOCK,
                                     "t0": header.get("t0"),
                                     "t": time.monotonic()})
                except LinkError as exc:
                    return str(exc)
            elif op == LinkOp.PING:
                try:
                    await link.send({"op": LinkOp.PONG,
                                     "t": header.get("t")})
                except LinkError as exc:
                    return str(exc)
            elif op in (LinkOp.STATS, LinkOp.TRACE):
                task = asyncio.ensure_future(self._probe_reply(op))
                self._probe_tasks.add(task)
                task.add_done_callback(self._probe_tasks.discard)
            elif op == LinkOp.HELLO:
                pass  # duplicate hello: harmless
            else:
                return f"unknown link op {op!r}"

    async def _probe_reply(self, op: str) -> None:
        reply = await self._on_probe(op)
        reply_header = ({"op": LinkOp.STATS} if op == LinkOp.STATS
                        else {"op": LinkOp.TRACE})
        try:
            await self._link.send(
                reply_header,
                json.dumps(reply or {}, separators=(",", ":")).encode())
        except LinkError:
            pass  # link died; the serve pump is already exiting

    def fail_inflight(self) -> None:
        self.sender.fail_all()

    async def close(self) -> None:
        for task in list(self._probe_tasks):
            task.cancel()
        await self._link.close()

    def stats(self) -> dict[str, Any]:
        return {**self.sender.stats, **self._gate.stats,
                "link": dict(self._link.stats)}
