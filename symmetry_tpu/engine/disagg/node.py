"""Prefill-tier node: the standalone half of a cross-machine disagg pair.

Runs on the PREFILL machine. Owns (a) a prefill engine host subprocess
(the same `engine/host.py` the local pair uses, `tpu.role: prefill`
derived from this node's config) and (b) the listening end of the
handoff link (`engine/disagg/net.py`): the decode-tier provider dials
`tpu.disagg.peer`, which is this node's `tpu.disagg.listen` address.

Data path (serial on purpose — the serial pump is the backpressure
chain the credit window feeds, see net.py):

    link submit/cancel ──▶ host stdin
    host stdout handoff lines ──▶ base64-decode ──▶ chunked, credit-
        gated, acked link transfer (HandoffSender)
    host stdout event lines ──▶ link `event` (prefill-tier terminal
        errors: tokenization failures, deadline sheds)
    link stats/trace probes ──▶ host stdin probe ──▶ reply + node-side
        link counters ride back over the link

Supervision is INDEPENDENT of the decode machine's: a dead or wedged
prefill host is respawned here with exponential backoff (warm compile
cache makes it cheap). While the host is down the node DROPS the link —
on the decode side that sheds every in-flight migration structured-
retryable (client failover) and triggers its reconnect-with-backoff
loop, which lands on the respawned host. Crossing machine boundaries,
"the pair restarts as one unit" (the local-pair model) is replaced by
"each tier restarts alone and the LINK is the failure domain between
them".

Run: python -m symmetry_tpu.engine.disagg.node <provider-config.yaml>
(the config needs `tpu.role: disagg` semantics only for deriving the
prefill tier; `tpu.disagg.listen` names the bind address).
"""

from __future__ import annotations

import asyncio
import base64
import contextlib
import json
import sys
import time
from typing import Any

from symmetry_tpu.engine.disagg.broker import derive_role_config
from symmetry_tpu.engine.disagg.net import (
    LinkConfig,
    LinkError,
    PrefillLink,
    link_transport,
    secure_link,
)
from symmetry_tpu.protocol.keys import HostOp, LinkOp
from symmetry_tpu.utils.faults import FAULTS
from symmetry_tpu.utils.logging import logger as log

# Handoff frames ride the host pipe as single base64 lines (~4/3 × raw
# KV bytes); same bound as the backend's disagg reader.
_HOST_PIPE_LIMIT = 1 << 30


class PrefillNode:
    """One prefill-tier node: prefill engine host + link listener."""

    def __init__(self, config: Any, *, listen: str | None = None) -> None:
        self._config = config
        self._link_cfg = LinkConfig(getattr(config.tpu, "disagg", None))
        self._listen = listen or self._link_cfg.listen
        if not self._listen:
            raise ValueError(
                "prefill node needs tpu.disagg.listen (or an explicit "
                "listen address)")
        # Pool identity: announced in the link hello so the decode
        # side's router names this member stably across reconnects.
        # Defaults to the (resolved) listen address.
        self._node_id: str | None = self._link_cfg.node_id
        self._draining = False
        sup = config.tpu.supervisor or {}
        self._backoff_base_s = float(sup.get("backoff_base_s", 0.5))
        self._backoff_max_s = float(sup.get("backoff_max_s", 15.0))
        self._max_respawns = int(sup.get("max_respawns", 3))
        self._min_stable_s = float(sup.get("min_stable_s", 5.0))
        self._stop_grace_s = float(sup.get("stop_grace_s", 30.0))
        self._proc: asyncio.subprocess.Process | None = None
        self._cfg_path: str | None = None
        self._listener = None
        self._plink: PrefillLink | None = None
        # (the link serve pump runs on the transport's accept-handler
        # task — see _on_connection; the node never owns it)
        self._pump_task: asyncio.Task | None = None
        self._supervisor_task: asyncio.Task | None = None
        self._host_down: asyncio.Event | None = None
        # Set when supervision gives up (max_respawns consecutive
        # short-lived host lives): the standalone entrypoint exits on
        # it; an INLINE node must never kill its embedding provider —
        # it just stops serving (listener closed, link dropped), and
        # the decode side sheds retryable on every dial.
        self.failed: asyncio.Event = asyncio.Event()
        self._spawned_at: float | None = None
        self._respawn_failures = 0
        self._stopped = False
        self._stats_waiters: list[asyncio.Future] = []
        self._trace_waiters: list[asyncio.Future] = []
        self.stats = {"links_accepted": 0, "host_restarts": 0,
                      "handoffs_pumped": 0}

    # ------------------------------------------------------------ address

    @property
    def address(self) -> str:
        """The dialable bound address (resolves tcp://host:0 → the real
        port) — the value the decode side's `tpu.disagg.peer` wants."""
        if self._listener is None:
            return self._listen
        return self._listener.address

    @property
    def node_id(self) -> str:
        return self._node_id or self.address

    @property
    def draining(self) -> bool:
        return self._draining

    # ---------------------------------------------------------- lifecycle

    def _host_argv(self, cfg_path: str) -> list[str]:
        """Command line for the prefill engine host. A seam on purpose
        (mirrors the backend's): tests substitute a protocol-faithful
        fake host to drive the link without a JAX build."""
        return [sys.executable, "-m", "symmetry_tpu.engine.host",
                cfg_path]

    async def start(self) -> None:
        import tempfile

        import yaml

        FAULTS.load(self._config.get("faults"))
        cfg = {k: v for k, v in self._config.get_all().items()
               if k != "apiKey"}
        with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                         delete=False) as fh:
            yaml.safe_dump(derive_role_config(cfg, "prefill"), fh)
            self._cfg_path = fh.name
        self._host_down = asyncio.Event()
        await self._spawn_host()
        transport = link_transport(self._listen)
        self._listener = await transport.listen(self._listen,
                                                self._on_connection)
        self._supervisor_task = asyncio.get_running_loop().create_task(
            self._supervise())
        log.info(f"prefill node up: host pid {self._proc.pid}, "
                 f"listening {self.address}")

    async def drain(self) -> None:
        """Deliberate drain: announce over the live link (the decode
        side's pool router excludes this member from NEW placements;
        in-flight work finishes here). Sticky across reconnects — a
        link that re-establishes mid-drain gets the announce again."""
        self._draining = True
        plink = self._plink
        if plink is not None and not plink.closed:
            with contextlib.suppress(LinkError):
                await plink.send_drain()
        log.info(f"prefill node {self.node_id}: draining")

    async def kill(self) -> None:
        """Chaos drill: die like a CRASHED node — no drain, no leave.
        The listener closes, the link cuts mid-whatever, the host is
        SIGKILLed. The decode side must account it as membership churn
        (member lost, in-flight re-placed), never as a clean leave."""
        self._stopped = True
        for task in (self._supervisor_task, self._pump_task):
            if task is not None:
                task.cancel()
        self._supervisor_task = self._pump_task = None
        if self._listener is not None:
            await self._listener.close()
            self._listener = None
        if self._plink is not None:
            await self._plink.close()
            self._plink = None
        if self._proc is not None:
            with contextlib.suppress(ProcessLookupError):
                self._proc.kill()
            with contextlib.suppress(Exception):
                await self._proc.wait()
            self._proc = None
        if self._cfg_path:
            import os

            with contextlib.suppress(OSError):
                os.unlink(self._cfg_path)
            self._cfg_path = None

    async def stop(self) -> None:
        self._stopped = True
        plink = self._plink
        if plink is not None and not plink.closed:
            # Departure is membership churn, not a fault: the leave
            # announce lets the router account it as such (best-effort —
            # a dead link already told the peer the louder way).
            with contextlib.suppress(LinkError):
                await plink.send_leave()
        for task in (self._supervisor_task, self._pump_task):
            if task is not None:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await task
        self._supervisor_task = self._pump_task = None
        if self._plink is not None:
            await self._plink.close()
            self._plink = None
        if self._listener is not None:
            await self._listener.close()
            self._listener = None
        if self._proc is not None:
            with contextlib.suppress(ConnectionError, OSError):
                await self._host_send_line(json.dumps(
                    {"op": HostOp.SHUTDOWN}).encode())
            try:
                await asyncio.wait_for(self._proc.wait(),
                                       self._stop_grace_s)
            except asyncio.TimeoutError:
                self._proc.kill()
                await self._proc.wait()
            self._proc = None
        if self._cfg_path:
            import os

            with contextlib.suppress(OSError):
                os.unlink(self._cfg_path)
            self._cfg_path = None

    # --------------------------------------------------------------- host

    async def _spawn_host(self) -> None:
        self._proc = await asyncio.create_subprocess_exec(
            *self._host_argv(self._cfg_path),
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            limit=_HOST_PIPE_LIMIT)
        # Read frames until ready (weight load + warmup happen first).
        while True:
            line = await self._proc.stdout.readline()
            if not line:
                rc = await self._proc.wait()
                raise RuntimeError(
                    f"prefill host died during startup (rc={rc})")
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if isinstance(msg, dict) and msg.get("op") == HostOp.READY:
                break
        self._spawned_at = time.monotonic()
        self._pump_task = asyncio.get_running_loop().create_task(
            self._pump_host())

    async def _host_send_line(self, line: bytes) -> None:
        proc = self._proc
        if (proc is None or proc.stdin is None
                or proc.stdin.is_closing()):
            raise ConnectionError("prefill host pipe unavailable")
        proc.stdin.write(line.rstrip(b"\n") + b"\n")
        await proc.stdin.drain()

    async def _pump_host(self) -> None:
        """Host stdout → link. Serial: a handoff transfer completes (or
        fails) before the next stdout line is read — that is how link
        backpressure reaches the host pipe and, through the handoff
        sink, the prefill scheduler's admissions."""
        proc = self._proc
        try:
            while True:
                line = await proc.stdout.readline()
                if not line:
                    break  # host exited
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(msg, dict):
                    continue
                op = msg.get("op")
                if op == HostOp.HANDOFF:
                    await self._pump_handoff(msg)
                elif op in (HostOp.EVENT, HostOp.EVENTS):
                    plink = self._plink
                    if plink is not None and not plink.closed:
                        with contextlib.suppress(LinkError):
                            await plink.send_event(msg)
                elif op == HostOp.STATS:
                    waiters, self._stats_waiters = self._stats_waiters, []
                    for w in waiters:
                        if not w.done():
                            w.set_result(msg)
                elif op == HostOp.TRACE:
                    waiters, self._trace_waiters = self._trace_waiters, []
                    for w in waiters:
                        if not w.done():
                            w.set_result(msg)
                # ready/clock replies outside a respawn window: ignore.
        except asyncio.CancelledError:
            raise  # respawn/stop cancelling us is not a host death
        except Exception as exc:  # noqa: BLE001 — pump must never die
            # silently: nobody would read host stdout again and every
            # request would hang while the node looks healthy. Treat it
            # as a host-life failure — supervision replaces the life.
            log.error(f"prefill node: host pump failed: {exc!r}")
        finally:
            if not self._stopped:
                self._host_down.set()

    async def _pump_handoff(self, msg: dict[str, Any]) -> None:
        plink = self._plink
        frame_b64 = msg.get("frame")
        if plink is None or plink.closed or not isinstance(frame_b64, str):
            return  # no link: the decode side owns request recovery
        try:
            frame = base64.b64decode(frame_b64, validate=True)
        except ValueError:
            log.error("prefill host emitted an undecodable handoff "
                      "frame; dropping it")
            return
        meta = {"id": str(msg.get("id", "")), "p": int(msg.get("p", 0)),
                "prompt_len": int(msg.get("prompt_len", 0)),
                "nbytes": len(frame),
                # Ledger accounting rides to the receiving broker: the
                # manifest's block count vs the blocks whose payload is
                # actually in this frame (the warm-handoff savings).
                "blocks": int(msg.get("blocks", 0)),
                "shipped": int(msg.get("shipped", 0))}
        self.stats["handoffs_pumped"] += 1
        ok = await plink.send_handoff(meta, frame)
        if not ok:
            log.warning(f"handoff {meta['id']} not delivered "
                        f"(link down or retries exhausted)")

    async def _forward_command(self, line: bytes) -> None:
        """Link submit/cancel → host stdin. A host that is mid-respawn
        (or not yet ready) cannot take the command — fail THAT request
        fast over the link with a retryable shed instead of letting the
        decode side's stream hang on a submit nobody holds."""
        try:
            await self._host_send_line(line)
            return
        except (ConnectionError, OSError):
            pass
        try:
            msg = json.loads(line)
        except ValueError:
            return
        if not isinstance(msg, dict) or msg.get("op") != HostOp.SUBMIT:
            return  # lost cancels are harmless (nobody is waiting)
        req_id = str(msg.get("id", ""))
        plink = self._plink
        if req_id and plink is not None and not plink.closed:
            with contextlib.suppress(LinkError):
                await plink.send_event(
                    {"op": HostOp.EVENT, "id": req_id, "text": "",
                     "done": True, "finish_reason": "error",
                     "restarting": True,
                     "error": "prefill host restarting"})

    async def _probe_host(self, op: str,
                          timeout: float = 10.0) -> dict | None:
        waiters = (self._stats_waiters if op == HostOp.STATS
                   else self._trace_waiters)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        waiters.append(fut)
        try:
            try:
                await self._host_send_line(
                    json.dumps({"op": op}).encode())
            except (ConnectionError, OSError):
                # Host down/mid-respawn: no reply is ever coming —
                # answer None NOW instead of holding the decode side's
                # equal-timeout link probe hostage for the full window.
                return None
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            if fut in waiters:
                waiters.remove(fut)

    # --------------------------------------------------------------- link

    async def _on_connection(self, conn) -> None:
        """Transport accept handler. One live link at a time: a
        reconnect (the decode side's backoff loop redialing after a
        drop) replaces the previous connection."""
        try:
            link = await secure_link(conn, self._link_cfg,
                                     initiator=False)
            plink = PrefillLink(link, self._link_cfg,
                                on_command=self._forward_command,
                                on_probe=self._link_probe,
                                node_id=self.node_id)
            await plink.handshake()
        except Exception as exc:  # noqa: BLE001 — reject bad dialers
            log.warning(f"handoff link handshake rejected: {exc}")
            await conn.close()
            return
        old, self._plink = self._plink, plink
        if old is not None:
            old.fail_inflight()
            await old.close()
        self.stats["links_accepted"] += 1
        log.info(f"handoff link accepted from {link.remote_address}")
        if self._draining:
            # Drain is sticky: a link re-established mid-drain must not
            # silently rejoin the placement set.
            with contextlib.suppress(LinkError):
                await plink.send_drain()
        # Serve inline on the handler task: the transport layer keeps it
        # alive until serve() returns (EOF / link error). The finally
        # guarantees a pump killed by ANY exception (malformed header
        # field, not just LinkError) still fails in-flight transfers
        # and clears the slot — otherwise the decode side keeps
        # forwarding submits into a connection nobody reads.
        try:
            reason = await plink.serve()
        except Exception as exc:  # noqa: BLE001 — see above
            reason = f"link pump error: {exc!r}"
        finally:
            plink.fail_inflight()
            if self._plink is plink:
                self._plink = None
            await plink.close()
        log.warning(f"handoff link closed ({reason})")

    async def _link_probe(self, op: str) -> dict | None:
        """stats/trace probe arriving over the link: host reply plus
        this node's own link-side counters."""
        host_op = (HostOp.STATS if op == LinkOp.STATS else HostOp.TRACE)
        reply = await self._probe_host(host_op)
        if op == LinkOp.TRACE:
            return reply
        plink = self._plink
        node = dict(self.stats)
        node["respawn_failures"] = self._respawn_failures
        if plink is not None:
            node.update(plink.stats())
        if FAULTS.enabled:
            node["faults"] = FAULTS.counters()
        return {"host": reply, "node": node}

    # --------------------------------------------------------- supervision

    async def _supervise(self) -> None:
        """Host death → drop the link (decode side sheds in-flight and
        reconnects), respawn with backoff; too many consecutive
        short-lived lives → give up and exit the node (the deployment
        layer restarts it; crash-looping forever helps nobody)."""
        while not self._stopped:
            await self._host_down.wait()
            self._host_down.clear()
            if self._stopped:
                return
            if (self._spawned_at is not None
                    and time.monotonic() - self._spawned_at
                    >= self._min_stable_s):
                self._respawn_failures = 0
            else:
                self._respawn_failures += 1
            plink, self._plink = self._plink, None
            if plink is not None:
                plink.fail_inflight()
                await plink.close()
            if self._pump_task is not None:
                self._pump_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await self._pump_task
                self._pump_task = None
            if self._proc is not None:
                with contextlib.suppress(ProcessLookupError):
                    self._proc.kill()
                with contextlib.suppress(Exception):
                    await self._proc.wait()
                self._proc = None
            while not self._stopped:
                if self._respawn_failures >= self._max_respawns:
                    log.error(
                        f"prefill node: {self._respawn_failures} "
                        f"consecutive failed host lives; giving up "
                        f"(listener closed; deployment layer restarts "
                        f"the node)")
                    if self._listener is not None:
                        await self._listener.close()
                        self._listener = None
                    self.failed.set()
                    return
                backoff = min(
                    self._backoff_max_s,
                    self._backoff_base_s
                    * (2 ** min(self._respawn_failures, 8)))
                log.warning(f"prefill node: respawning host in "
                            f"{backoff:.2f}s")
                await asyncio.sleep(backoff)
                try:
                    await self._spawn_host()
                except Exception as exc:  # noqa: BLE001 — spawn failed
                    self._respawn_failures += 1
                    log.error(f"prefill node: host respawn failed: {exc}")
                    continue
                self.stats["host_restarts"] += 1
                log.warning(f"prefill node: host respawned "
                            f"(pid {self._proc.pid})")
                break


async def _serve(config_path: str) -> int:
    from symmetry_tpu.provider.config import ConfigManager

    config = ConfigManager(config_path=config_path)
    node = PrefillNode(config)
    await node.start()
    stop = asyncio.Event()
    import signal

    loop = asyncio.get_running_loop()
    # SIGTERM = deliberate drain: announce (router stops placing here),
    # let in-flight work finish for drain_grace_s, then leave + exit.
    # A second SIGTERM — or SIGINT — stops immediately.
    grace_s = float((getattr(config.tpu, "disagg", None) or {})
                    .get("drain_grace_s", 30.0))

    drain_started = False

    def _on_term() -> None:
        # Flag locally, not via node.draining: the drain() task may not
        # have RUN yet when a rapid second SIGTERM arrives — that second
        # signal must stop now, not arm another grace timer.
        nonlocal drain_started
        if drain_started:
            stop.set()
        else:
            drain_started = True
            asyncio.ensure_future(node.drain())
            loop.call_later(grace_s, stop.set)

    with contextlib.suppress(NotImplementedError):
        loop.add_signal_handler(signal.SIGTERM, _on_term)
    with contextlib.suppress(NotImplementedError):
        loop.add_signal_handler(signal.SIGINT, stop.set)
    try:
        _, pending = await asyncio.wait(
            [asyncio.ensure_future(stop.wait()),
             asyncio.ensure_future(node.failed.wait())],
            return_when=asyncio.FIRST_COMPLETED)
        for fut in pending:
            fut.cancel()  # a pending waiter at loop teardown is stderr
            # noise ("Task was destroyed…") in the logs verify greps
    finally:
        failed = node.failed.is_set()
        await node.stop()
    return 86 if failed else 0


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: python -m symmetry_tpu.engine.disagg.node "
              "<config.yaml>", file=sys.stderr)
        return 2
    return asyncio.new_event_loop().run_until_complete(
        _serve(sys.argv[1]))


if __name__ == "__main__":
    sys.exit(main())
