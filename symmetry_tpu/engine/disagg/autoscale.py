"""symscale: the SLO-goodput autoscaler closing telemetry → topology.

Every piece existed before this module and nothing connected them: PR 11
gave the pools their actuators (join / drain / leave, per-member
respawn), PR 10 gave SLO burn rates and queue gauges, PR 15 gave
symprof's measured device-seconds per tier — yet the M×N tier shape
stayed a hand-picked constant. This module is the controller in the
middle, shaped after DistServe's goodput objective and Splitwise's
phase-pool sizing (PAPERS.md): maximize SLO-attaining tokens per
chip-second, where chip-seconds = Σ member-alive time.

    SloMonitor.burn_rates() ──ttft──────────▶ prefill pressure
                            ──inter_chunk──▶ decode pressure
    PoolRouter gauges ──in-flight + queue_depth──▶ per-tier load
    symprof device_s_total ──per-tier busy deltas─▶ measured M:N ratio
                                │
                                ▼  PoolAutoscaler.tick()  (one per pool
                                │  heartbeat; pure state, injectable
                                │  clock — unit-testable in µs)
                                ▼
    {spawn prefill | spawn decode | drain idlest | rebalance | hold}
                                │
                                ▼  tpu_native member factory (real
                                   _DecodeMember / PrefillNode
                                   lifecycle events)

The controller is PURE STATE like PoolRouter: it never spawns, drains,
sleeps, or reads a wall clock it wasn't given. The backend feeds it one
sensor snapshot per pool heartbeat and applies whatever single decision
comes back. Stability is structural, not tuned:

  dwell     a minimum quiet period between topology changes — the
            system must settle before the sensors mean anything again
  cooldown  after churn (a member died and the supervisor respawned
            it), scaling pauses entirely: respawn turbulence looks
            exactly like a load spike, and reacting to it would flap.
            Churn respawns are NOT scaling decisions and never count
            as one.
  floor     1×1 — the drain path refuses the last placeable member of
            a tier (PoolRouter.drain refuses it independently: two
            locks on the same door)
  ceiling   `tpu.autoscale.max_members` per tier

Every tick books a structured decision record — action, reason, the
full input snapshot, and goodput-at-decision — into a bounded ring
(flight-recorder-visible through engine stats) and the
`sym_autoscale_*` metric families. Only real topology changes increment
the decision counter: symtop's SCALE column means "the shape moved",
not "the controller woke up".
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable

from symmetry_tpu.engine.disagg.pool import (
    DECODE,
    PREFILL,
    MemberState,
    PoolRouter,
)
from symmetry_tpu.utils.metrics import METRICS, MetricName

TIERS = (PREFILL, DECODE)

# Decision actions (wire-visible in the decision log / metrics labels).
SPAWN = "spawn"
DRAIN = "drain"
REBALANCE = "rebalance"
HOLD = "hold"


class AutoscaleConfig:
    """The `tpu.autoscale` mapping. Present ⇒ the pool heartbeat ticks
    a PoolAutoscaler; absent ⇒ the shape stays whatever `pool:` said.

    Keys (all optional; defaults are deliberately conservative — a
    controller that scales rarely beats one that flaps):
      enabled          master switch (default true when block present)
      max_members      per-tier ceiling (default 4)
      dwell_s          min seconds between topology decisions (30)
      churn_cooldown_s scaling pause after a churn respawn (60)
      spawn_burn       fast-window SLO burn that triggers a spawn (1.0
                       = error budget burning at exactly the
                       sustainable rate)
      spawn_queue      avg per-member load (in-flight + queue depth)
                       that triggers a spawn (2.0)
      spawn_queue_ticks consecutive over-threshold ticks before a
                       queue-driven spawn fires (3). Burn is already a
                       windowed rate; the load gauge is an instant
                       sample, and one arrival clump that drains within
                       a heartbeat must not buy a member boot
      drain_load       avg per-member load at-or-under which a tier
                       counts as idle (0.25)
      drain_ticks      consecutive idle ticks before the idlest member
                       drains (3)
      min_busy_s       per-tick device-busy signal (both tiers summed)
                       below which the measured-ratio rebalance stays
                       quiet — don't reshape on noise (0.05)
    """

    def __init__(self, raw: dict[str, Any] | None) -> None:
        d = dict(raw or {})
        self.enabled: bool = bool(d) and bool(d.get("enabled", True))
        self.max_members: int = max(int(d.get("max_members", 4)), 1)
        self.dwell_s: float = max(float(d.get("dwell_s", 30.0)), 0.0)
        self.churn_cooldown_s: float = max(
            float(d.get("churn_cooldown_s", 60.0)), 0.0)
        self.spawn_burn: float = max(float(d.get("spawn_burn", 1.0)), 1e-9)
        self.spawn_queue: float = max(
            float(d.get("spawn_queue", 2.0)), 1e-9)
        self.spawn_queue_ticks: int = max(
            int(d.get("spawn_queue_ticks", 3)), 1)
        self.drain_load: float = max(float(d.get("drain_load", 0.25)), 0.0)
        self.drain_ticks: int = max(int(d.get("drain_ticks", 3)), 1)
        self.min_busy_s: float = max(float(d.get("min_busy_s", 0.05)), 0.0)

    def to_dict(self) -> dict[str, Any]:
        return {"enabled": self.enabled, "max_members": self.max_members,
                "dwell_s": self.dwell_s,
                "churn_cooldown_s": self.churn_cooldown_s,
                "spawn_burn": self.spawn_burn,
                "spawn_queue": self.spawn_queue,
                "spawn_queue_ticks": self.spawn_queue_ticks,
                "drain_load": self.drain_load,
                "drain_ticks": self.drain_ticks,
                "min_busy_s": self.min_busy_s}


# Decision-record ring size: enough for hours at sane dwell settings,
# bounded so engine stats / flight dumps stay fixed-size.
DECISION_RING = 256

# Measured-ratio memory: per-tier busy deltas accumulate into a
# geometric window (delta + DECAY × previous) so the M:N signal tracks
# the recent minutes, not the whole run's history.
BUSY_DECAY = 0.8


class PoolAutoscaler:
    """One pool's scaling controller: sensors in, at most ONE topology
    op out per tick.

    Thread contract: same as PoolRouter — every call happens on the
    backend's event loop. `clock` is injectable; tests drive dwell,
    cooldown, and idle-streak logic deterministically in microseconds.

    `grow_prefill` gates the prefill tier's actuators: a pool dialing
    REMOTE prefill peers has no machine to spawn one on, so prefill
    stays fixed and only the decode tier scales.
    """

    def __init__(self, config: AutoscaleConfig, router: PoolRouter, *,
                 clock: Callable[[], float] = time.monotonic,
                 grow_prefill: bool = True) -> None:
        self.config = config
        self.router = router
        self._clock = clock
        self.grow_prefill = grow_prefill
        self._decisions: deque = deque(maxlen=DECISION_RING)
        self._last_action_t: float | None = None   # None → first action free
        self._cooldown_until = 0.0
        self._idle_ticks = {PREFILL: 0, DECODE: 0}
        self._press_ticks = {PREFILL: 0, DECODE: 0}
        self._prev_nonlost: dict[str, int] | None = None
        self._busy = {PREFILL: 0.0, DECODE: 0.0}   # decayed busy window
        self._target: dict[str, int] | None = None
        self.counters = {"ticks": 0, "holds": 0, "spawns": 0,
                         "drains": 0, "rebalances": 0, "dwell_holds": 0,
                         "cooldown_holds": 0, "churn_cooldowns": 0}
        self._m_decisions = METRICS.counter(
            MetricName.AUTOSCALE_DECISIONS,
            "autoscaler topology decisions (holds excluded)",
            labels=("action", "tier"))
        self._m_target = METRICS.gauge(
            MetricName.AUTOSCALE_TARGET,
            "autoscaler's desired member count per tier",
            labels=("tier",))
        self._m_chip = METRICS.gauge(
            MetricName.AUTOSCALE_CHIP_SECONDS,
            "pool chip-seconds (sum of member-alive time)")
        self._m_goodput = METRICS.gauge(
            MetricName.AUTOSCALE_GOODPUT,
            "SLO-attaining tokens per chip-second at last tick")
        self._m_tokens_raw = METRICS.gauge(
            MetricName.AUTOSCALE_TOKENS_RAW,
            "cumulative raw token count (pre-ledger goodput numerator, "
            "kept for series continuity)")

    # ----------------------------------------------------------- sensors

    def note_churn(self) -> None:
        """A member died and the supervisor is respawning it. This is
        capacity repair, not a scaling decision — no record is booked,
        no counter labeled `action` moves. It DOES open the cooldown:
        respawn turbulence (re-placements, a cold cache, a joining
        member) is indistinguishable from a load spike, and scaling on
        it would flap."""
        self._cooldown_until = self._clock() + self.config.churn_cooldown_s
        self.counters["churn_cooldowns"] += 1

    # -------------------------------------------------------------- tick

    def tick(self, *, burn: dict[str, float] | None = None,
             busy_delta_s: dict[str, float] | None = None,
             tokens_total: float | None = None,
             tokens_raw: float | None = None,
             applying: bool = False) -> dict[str, Any]:
        """One control step. Inputs: per-SLO fast-window burns
        (SloMonitor.burn_rates()), per-tier device-busy-second deltas
        since the last tick (symprof's measured ratio signal), the
        cumulative SLO-ATTAINING token count (the goodput numerator —
        the ledger's per-request attainment fold; ROADMAP item 5 and
        DistServe define goodput over tokens that met their SLO, not
        all tokens), the raw cumulative count (`tokens_raw`, kept as
        the sym_autoscale_tokens_raw continuity series — pre-ledger
        callers that still pass only tokens_total get the old
        behavior), and whether the previous decision is still being
        applied. Returns the decision record — every tick produces
        one, holds included; only non-hold records change the topology
        (and the decision counter)."""
        now = self._clock()
        cfg = self.config
        self.counters["ticks"] += 1
        burn = burn or {}
        for tier in TIERS:
            delta = max(float((busy_delta_s or {}).get(tier, 0.0)), 0.0)
            self._busy[tier] = self._busy[tier] * BUSY_DECAY + delta

        # --- sensor snapshot (this dict IS the decision record's input)
        placeable = {t: 0 for t in TIERS}
        nonlost = {t: 0 for t in TIERS}
        load = {t: 0.0 for t in TIERS}
        for m in self.router.members():
            if m.state != MemberState.LOST:
                nonlost[m.tier] += 1
            if m.placeable:
                placeable[m.tier] += 1
                load[m.tier] += len(m.in_flight) + m.queue_depth
        avg_load = {t: (load[t] / placeable[t] if placeable[t] else 0.0)
                    for t in TIERS}
        # SLO → tier mapping: TTFT is made in the prefill tier,
        # inter-chunk gaps in the decode tier; e2e implicates whichever
        # is already under more pressure, so it feeds both.
        e2e = float(burn.get("e2e", 0.0))
        tier_burn = {PREFILL: max(float(burn.get("ttft", 0.0)), e2e),
                     DECODE: max(float(burn.get("inter_chunk", 0.0)), e2e)}
        chip_s = self.router.chip_seconds()
        goodput = (round(float(tokens_total) / chip_s, 4)
                   if tokens_total is not None and chip_s > 1e-9 else None)
        inputs = {
            "burn": {t: round(tier_burn[t], 3) for t in TIERS},
            "avg_load": {t: round(avg_load[t], 3) for t in TIERS},
            "members": dict(placeable),
            "busy_s": {t: round(self._busy[t], 4) for t in TIERS},
            "tokens_total": tokens_total,
        }
        if tokens_raw is not None:
            inputs["tokens_raw"] = tokens_raw

        # Streaks advance every tick, decision or not. IDLE: a tier is
        # idle when its load sits under the drain floor AND its burn is
        # comfortably inside budget (draining a tier that is burning
        # would trade chips for an outage). PRESSURE: the queue-spawn
        # trigger — burn is already a windowed rate, but the load gauge
        # is an instant sample, so a spawn needs spawn_queue_ticks
        # consecutive over-threshold ticks (one arrival clump that
        # drains within a heartbeat must not buy a member boot). Two
        # freezes keep both streaks honest: while a previous decision
        # is still being applied the streaks hold (a member booting for
        # seconds would otherwise bank enough "idle" to be drained the
        # instant it joins — or enough "pressure" from its own boot
        # degradation to spawn again), and a tier whose membership just
        # changed restarts from zero — the new topology gets a full
        # observation window.
        for tier in TIERS:
            if (self._prev_nonlost is not None
                    and nonlost[tier] != self._prev_nonlost[tier]):
                self._idle_ticks[tier] = 0
                self._press_ticks[tier] = 0
            elif applying:
                pass
            else:
                if (avg_load[tier] <= cfg.drain_load
                        and tier_burn[tier] < cfg.spawn_burn / 2.0):
                    self._idle_ticks[tier] += 1
                else:
                    self._idle_ticks[tier] = 0
                if avg_load[tier] >= cfg.spawn_queue:
                    self._press_ticks[tier] += 1
                else:
                    self._press_ticks[tier] = 0
        self._prev_nonlost = dict(nonlost)

        if self._target is None:
            self._target = {t: max(nonlost[t], 1) for t in TIERS}

        action, reason, extra = self._decide(
            now, tier_burn, avg_load, placeable, nonlost, applying)

        record: dict[str, Any] = {
            "t": round(now, 4), "action": action, "reason": reason,
            "inputs": inputs, "chip_s": round(chip_s, 3),
            "goodput_tokens_per_chip_s": goodput, **extra}
        self._decisions.append(record)

        if action != HOLD:
            self._last_action_t = now
            if action == SPAWN:
                self.counters["spawns"] += 1
                tier = extra["tier"]
                self._target[tier] = min(
                    self._target[tier] + 1, cfg.max_members)
                self._idle_ticks[tier] = 0
                self._press_ticks[tier] = 0
                self._m_decisions.inc(action=SPAWN, tier=tier)
            elif action == DRAIN:
                self.counters["drains"] += 1
                tier = extra["tier"]
                self._target[tier] = max(self._target[tier] - 1, 1)
                self._idle_ticks[tier] = 0
                self._m_decisions.inc(action=DRAIN, tier=tier)
            elif action == REBALANCE:
                self.counters["rebalances"] += 1
                grow, shrink = extra["spawn_tier"], extra["drain_tier"]
                self._target[grow] = min(
                    self._target[grow] + 1, cfg.max_members)
                self._target[shrink] = max(self._target[shrink] - 1, 1)
                self._idle_ticks[grow] = 0
                self._idle_ticks[shrink] = 0
                self._press_ticks[grow] = 0
                self._press_ticks[shrink] = 0
                self._m_decisions.inc(action=REBALANCE, tier=grow)
        else:
            self.counters["holds"] += 1

        for tier in TIERS:
            self._m_target.set(self._target[tier], tier=tier)
        self._m_chip.set(round(chip_s, 3))
        if goodput is not None:
            self._m_goodput.set(goodput)
        if tokens_raw is not None:
            self._m_tokens_raw.set(round(float(tokens_raw), 1))
        return record

    # ----------------------------------------------------------- policy

    def _decide(self, now: float, tier_burn: dict[str, float],
                avg_load: dict[str, float], placeable: dict[str, int],
                nonlost: dict[str, int], applying: bool
                ) -> tuple[str, str, dict[str, Any]]:
        """The priority ladder: gates (applying / cooldown) → spawn
        (SLO protection first) → measured-ratio rebalance → idle drain
        → hold. One action per tick, dwell-gated."""
        cfg = self.config
        if not cfg.enabled:
            return HOLD, "disabled", {}
        if applying:
            return HOLD, "applying_previous_decision", {}
        if now < self._cooldown_until:
            self.counters["cooldown_holds"] += 1
            return HOLD, "churn_cooldown", {}
        dwell_blocked = (self._last_action_t is not None
                         and now - self._last_action_t < cfg.dwell_s)

        # --- spawn: a tier over its burn threshold, or over its queue
        # threshold for spawn_queue_ticks consecutive ticks; worst
        # normalized pressure wins; ceiling counts every non-lost
        # member (a joining spawn-in-progress occupies a slot).
        best_tier, best_pressure = None, 0.0
        for tier in TIERS:
            over = (tier_burn[tier] >= cfg.spawn_burn
                    or self._press_ticks[tier] >= cfg.spawn_queue_ticks)
            if not over:
                continue
            if tier == PREFILL and not self.grow_prefill:
                continue
            if nonlost[tier] >= cfg.max_members:
                continue
            pressure = (tier_burn[tier] / cfg.spawn_burn
                        + avg_load[tier] / cfg.spawn_queue)
            if pressure > best_pressure:
                best_tier, best_pressure = tier, pressure
        if best_tier is not None:
            if dwell_blocked:
                self.counters["dwell_holds"] += 1
                return HOLD, f"dwell({best_tier} spawn wanted)", {}
            return SPAWN, (
                f"{best_tier}: burn {tier_burn[best_tier]:.2f} "
                f"load {avg_load[best_tier]:.2f} over threshold"), {
                "tier": best_tier}

        # --- rebalance: symprof's measured per-tier device cost says
        # the M:N split is wrong. desired_prefill = total × share of
        # busy time the prefill tier actually consumed, clamped to
        # keep both tiers ≥ 1. Only moves when the shrinking tier is
        # idle (otherwise the spawn path already owns the problem) and
        # the busy signal is big enough to be meaning, not noise.
        total_busy = self._busy[PREFILL] + self._busy[DECODE]
        total = placeable[PREFILL] + placeable[DECODE]
        if total_busy >= cfg.min_busy_s and total >= 3:
            share = self._busy[PREFILL] / total_busy
            desired_prefill = min(max(round(total * share), 1), total - 1)
            diff = desired_prefill - placeable[PREFILL]
            if diff != 0:
                grow = PREFILL if diff > 0 else DECODE
                shrink = DECODE if diff > 0 else PREFILL
                ok = (avg_load[shrink] <= cfg.drain_load
                      and placeable[shrink] > 1
                      and nonlost[grow] < cfg.max_members
                      and (grow != PREFILL or self.grow_prefill))
                if ok:
                    if dwell_blocked:
                        self.counters["dwell_holds"] += 1
                        return HOLD, "dwell(rebalance wanted)", {}
                    member = self._idlest(shrink)
                    if member is not None:
                        return REBALANCE, (
                            f"measured ratio: prefill busy share "
                            f"{share:.2f} wants {desired_prefill}/"
                            f"{total} prefill"), {
                            "spawn_tier": grow, "drain_tier": shrink,
                            "member": member}

        # --- idle drain: a tier idle for drain_ticks consecutive ticks
        # gives back its idlest member. Floor: never the last one.
        for tier in TIERS:
            if (self._idle_ticks[tier] >= cfg.drain_ticks
                    and placeable[tier] > 1):
                if dwell_blocked:
                    self.counters["dwell_holds"] += 1
                    return HOLD, f"dwell({tier} drain wanted)", {}
                member = self._idlest(tier)
                if member is not None:
                    return DRAIN, (
                        f"{tier} idle {self._idle_ticks[tier]} ticks "
                        f"(load {avg_load[tier]:.2f})"), {
                        "tier": tier, "member": member}

        return HOLD, "steady", {}

    def _idlest(self, tier: str) -> str | None:
        """The drain victim: least loaded placeable member, lifetime
        placements then id as the deterministic tie-break."""
        live = [m for m in self.router.members(tier) if m.placeable]
        if not live:
            return None
        m = min(live, key=lambda m: (len(m.in_flight) + m.queue_depth,
                                     m.placements, m.member_id))
        return m.member_id

    # -------------------------------------------------------------- views

    @property
    def target(self) -> dict[str, int]:
        return dict(self._target or {})

    def decision_log(self) -> list[dict[str, Any]]:
        """The full bounded ring, oldest first (bench artifact)."""
        return list(self._decisions)

    def stats(self) -> dict[str, Any]:
        """Engine-stats / flight-recorder block: config, counters,
        convergence view, and the recent decision tail."""
        now = self._clock()
        return {
            "config": self.config.to_dict(),
            **self.counters,
            "target": dict(self._target or {}),
            "cooldown_remaining_s": round(
                max(self._cooldown_until - now, 0.0), 3),
            "idle_ticks": dict(self._idle_ticks),
            "press_ticks": dict(self._press_ticks),
            "decisions": [
                {k: v for k, v in d.items() if k != "inputs"}
                for d in list(self._decisions)[-16:]],
            # Non-hold records survive here even when a long applying
            # window floods the tick tail with holds (a member boot is
            # ~seconds of heartbeats).
            "actions": [
                {k: v for k, v in d.items() if k != "inputs"}
                for d in list(self._decisions)
                if d["action"] != HOLD][-16:],
        }
