"""Handoff broker: request-state migration between the two engine tiers.

The `tpu_native` backend in `tpu.role: disagg` mode runs TWO engine
hosts — a prefill host (admissions + chunked prefill only) and a decode
host (generation only). A request's life then spans three owners:

    provider submit ──▶ prefill host          (tokenize, build prefix KV)
                          │  {"op":"handoff"} (versioned frame, frames.py)
    broker ◀──────────────┘
      │  {"op":"adopt"}  (frame + the request state the decode
      ▼                   host needs: max_new, sampling, deadline …)
    decode host ──▶ token events ──▶ provider queues (unchanged path)

This module is the process-free middle: it remembers what was submitted
(so the adopt op can re-attach sampling/max_new/deadline to the frame
without the prefill host round-tripping them), rebases deadlines onto
the time already spent in the prefill tier, and accounts the handoff
itself (frames, bytes, prefix tokens shipped, per-request prefill-tier
latency) for the stats → provider stats → bench chain. The asyncio
plumbing — spawning the two hosts, pumping their pipes, supervising
their deaths — stays in the backend, which makes this class unit-
testable without a single subprocess.
"""

from __future__ import annotations

import copy
import time
from typing import Any

from symmetry_tpu.protocol.keys import HostOp
from symmetry_tpu.utils.metrics import METRICS, MetricName
from symmetry_tpu.utils.trace import Histogram, Tracer

# The decode tier adopts handoff frames through its prefix store; a
# decode host configured without one could only ever full-prefill, which
# silently re-does the prefill tier's work. When the operator set no
# budget, the broker gives the decode tier this much (the working set is
# transient — entries churn through LRU the moment their request admits).
# This is only the config-level seed: the decode-role ENGINE raises its
# store budget to a geometry-derived floor (2 × largest-bucket entry
# bytes) at construction, so adoption of big-bucket prompts is never
# budget-rejected by a default too small for the model at hand.
DEFAULT_DECODE_PREFIX_MB = 64.0


def derive_role_config(base: dict[str, Any], role: str) -> dict[str, Any]:
    """The per-tier host config: the provider's config with `tpu.role`
    pinned to the tier and any `tpu.disagg.<role>` overrides applied.
    Override mapping keys land in the tpu section, except `faults`,
    which lands top-level (the host loads faults from there) — this is
    how a chaos test arms a seam in ONE tier of the pair."""
    if role not in ("prefill", "decode"):
        raise ValueError(f"derive_role_config: bad role {role!r}")
    cfg = copy.deepcopy(base)
    tpu = dict(cfg.get("tpu") or {})
    disagg = tpu.pop("disagg", None) or {}
    overrides = dict(disagg.get(role) or {})
    faults = overrides.pop("faults", None)
    tpu.update(overrides)
    tpu["role"] = role
    if role == "decode" and not tpu.get("prefix_cache_mb"):
        tpu["prefix_cache_mb"] = DEFAULT_DECODE_PREFIX_MB
    if role == "prefill" and not tpu.get("prefix_cache_mb"):
        # The prefill tier's radix cache is what session-affine pool
        # routing monetizes: turn N+1 of a conversation re-placed on
        # the member holding turn N's prefix KV skips that prefill
        # work entirely, and the cache summary it gossips is the
        # router's affinity signal. Same geometry constraints the
        # decode default already imposes (prefix_block divides every
        # bucket), so no config that ran disagg before can newly fail.
        tpu["prefix_cache_mb"] = DEFAULT_DECODE_PREFIX_MB
    if role == "prefill" and "pipeline_depth" not in overrides:
        # A prefill tier never decodes: there are no blocks to keep in
        # flight, so the emit worker would idle next to admission-only
        # traffic. Depth 1 keeps its emit path inline (override-able
        # per tier via tpu.disagg.prefill.pipeline_depth).
        tpu["pipeline_depth"] = 1
    cfg["tpu"] = tpu
    if faults:
        merged = dict(cfg.get("faults") or {})
        merged.update(faults)
        cfg["faults"] = merged
    return cfg


class HandoffBroker:
    """Pending-request state + handoff accounting for one host pair.

    Thread contract: all calls happen on the backend's event loop (the
    two pipe readers and stream() all live there), so no locking."""

    def __init__(self) -> None:
        # request id -> (submit fields the decode host will need,
        #               submit monotonic stamp,
        #               prefill member holding the migration — None for
        #               the pair, a pool member id in pool mode)
        self._pending: dict[str, tuple[dict[str, Any], float,
                                       str | None]] = {}
        # Per-DESTINATION ledger accounting (pool topology): blocks
        # covered / actually shipped per adopting member, so the smoke
        # and symtop can see that warm handoffs to a specific member
        # ship only tail blocks. Keyed by the member id adopt_op was
        # told; the fixed pair books under "decode".
        self.member_ledger: dict[str, dict[str, int]] = {}
        self.counters = {"submitted": 0, "handoff_frames": 0,
                         "handoff_bytes": 0, "prefix_tokens": 0,
                         "routing_only": 0, "dropped": 0,
                         # Block-manifest ledger (frames v2): blocks the
                         # manifests covered vs blocks whose payload
                         # actually rode the wire — manifest-only blocks
                         # were adopted by reference on the decode tier
                         # (the incremental-handoff savings).
                         "blocks": 0, "blocks_shipped": 0,
                         # Warm handoffs: frames that shipped strictly
                         # fewer blocks than their manifest covered —
                         # the destination already held the rest.
                         "warm_frames": 0,
                         # The WIRE leg of the handoff (serialize time
                         # lives host-side in handoff_stats): pipe hop
                         # for the local pair, chunked link transfer in
                         # network mode. Zero until a handoff carries a
                         # stamp or a precomputed wire_s.
                         "wire_frames": 0, "wire_bytes": 0,
                         "wire_s_total": 0.0}
        # Prefill-tier residence per request: provider submit → handoff
        # frame back at the broker. THE disagg latency number — what the
        # decode tier's TTFT no longer has to contain.
        self.prefill_tier_hist = Histogram()
        # Handoff wire latency per frame: emit stamp (prefill host pipe
        # write, or the link sender's transfer start) → frame back at
        # this broker. Emit stamps from the other tier's clock are
        # mapped through `prefill_clock_offset` — the host-pipe
        # handshake offset locally, the link handshake offset across
        # machines — so the split survives skewed clocks.
        self.wire_hist = Histogram()
        self.prefill_clock_offset = 0.0
        # The wire leg as SPANS too: one "handoff_wire" span per frame
        # (start = receipt − wire, stamps on this process's clock), so
        # the merged Perfetto timeline shows the handoff crossing the
        # pipe/link between the prefill tier's rows and the decode
        # tier's adopt_dispatch rows.
        self.tracer = Tracer()
        # Always-on registry series (utils/metrics.py, provider-process
        # registry): the handoff ledger as scrape-able families beside
        # the stats() snapshot.
        self._m_frames = METRICS.counter(
            MetricName.HANDOFF_FRAMES, "handoff frames migrated")
        self._m_bytes = METRICS.counter(
            MetricName.HANDOFF_BYTES, "handoff frame bytes migrated")
        self._m_pending = METRICS.gauge(
            MetricName.HANDOFF_PENDING,
            "requests submitted to the prefill tier, frame not yet back")
        self._m_wire = METRICS.histogram(
            MetricName.HANDOFF_WIRE, "handoff wire leg per frame")
        self._m_prefill_tier = METRICS.histogram(
            MetricName.HANDOFF_PREFILL_TIER,
            "prefill-tier residency per request (submit to frame back)")

    # ------------------------------------------------------------- state

    def note_submit(self, request_id: str,
                    submit: dict[str, Any]) -> None:
        """Remember the request state that must survive the migration.
        `submit` is the host-pipe submit op; only the decode-relevant
        fields are kept (messages stay behind — tokens ride the frame).
        The entry's member slot starts None (the fixed pair never sets
        it); the elastic pool writes it through reassign() once the
        placed submit is actually delivered."""
        keep = {k: submit[k] for k in
                ("max_new", "sampling", "speculative", "trace", "deadline_s",
                 "resume")
                if k in submit}
        self._pending[request_id] = (keep, time.monotonic(), None)
        self.counters["submitted"] += 1
        self._m_pending.set(len(self._pending))

    def reassign(self, request_id: str, member: str | None) -> None:
        """Re-placement: the migration moved to another member. The
        submit stamp is PRESERVED — deadline rebasing stays anchored to
        the provider submit, so churn never refunds a deadline."""
        entry = self._pending.get(request_id)
        if entry is not None:
            self._pending[request_id] = (entry[0], entry[1], member)

    def pending_on(self, member: str) -> list[str]:
        """Request ids whose migration is pending on ONE member
        (non-destructive — the re-placement path keeps the entries so
        the eventual handoff still finds its state). The member-down
        path unions this with the router's own view: the broker is
        authoritative for 'submitted but not yet adopted', so a
        migration the router lost track of still gets re-placed."""
        return [rid for rid, (_, _, m) in self._pending.items()
                if m == member]

    def forget(self, request_id: str) -> None:
        """The request ended on the prefill tier (tokenization error,
        admission error, deadline shed, cancel) — nothing to migrate."""
        if self._pending.pop(request_id, None) is not None:
            self.counters["dropped"] += 1
            self._m_pending.set(len(self._pending))

    def fail_all(self) -> None:
        """Host pair is going down: every pending migration is dead (the
        streams are failed by the backend's shed path)."""
        self.counters["dropped"] += len(self._pending)
        self._pending.clear()
        self._m_pending.set(0)

    def shed_pending(self) -> list[str]:
        """The handoff LINK died (network mode): every request whose
        migration was in flight is unrecoverable on this path — return
        their ids so the backend can shed each stream structured-
        retryable (the client fails over / retries through the
        reconnect window). Requests already adopted by the decode tier
        are untouched; they no longer need the prefill tier."""
        ids = list(self._pending)
        self.counters["dropped"] += len(ids)
        self._pending.clear()
        self._m_pending.set(0)
        return ids

    @property
    def pending(self) -> int:
        return len(self._pending)

    def is_pending(self, request_id: str) -> bool:
        """True while a submit awaits its handoff frame — lets callers
        route the adopting member BEFORE adopt_op pops the entry."""
        return request_id in self._pending

    # ------------------------------------------------------------ handoff

    def adopt_op(self, handoff: dict[str, Any],
                 member: str | None = None) -> dict[str, Any] | None:
        """One prefill-host `handoff` op → the decode-host `adopt` op,
        with the remembered request state re-attached and the deadline
        rebased by the prefill-tier time already spent. `member` is the
        adopting decode member (pool mode) — its per-member ledger
        books the blocks covered vs shipped. None when the request is
        unknown (already cancelled/failed — drop the frame, nobody is
        waiting)."""
        req_id = str(handoff.get("id", ""))
        entry = self._pending.pop(req_id, None)
        if entry is None:
            return None
        keep, t_submit, _member = entry
        now = time.monotonic()
        self.prefill_tier_hist.observe(now - t_submit)
        self._m_prefill_tier.observe(now - t_submit)
        self.counters["handoff_frames"] += 1
        nbytes = int(handoff.get("nbytes", 0))
        self.counters["handoff_bytes"] += nbytes
        self._m_frames.inc()
        self._m_bytes.inc(nbytes)
        self._m_pending.set(len(self._pending))
        # Wire-leg split: either precomputed by the link receiver
        # ("wire_s", network mode — it holds the measured link offset)
        # or derived here from the prefill host's emit stamp ("t")
        # mapped through the host-pipe clock offset (local pair).
        wire = handoff.get("wire_s")
        if wire is None and handoff.get("t") is not None:
            wire = max(now - (float(handoff["t"])
                              - self.prefill_clock_offset), 0.0)
        if wire is not None:
            wire = float(wire)
            self.wire_hist.observe(wire)
            self._m_wire.observe(wire)
            self.counters["wire_frames"] += 1
            self.counters["wire_bytes"] += nbytes
            self.counters["wire_s_total"] += wire
            if self.tracer.enabled:
                self.tracer.record("handoff_wire", now - wire, wire,
                                   request_id=req_id, bytes=nbytes)
        p = int(handoff.get("p", 0))
        self.counters["prefix_tokens"] += p
        blocks = int(handoff.get("blocks", 0))
        shipped = int(handoff.get("shipped", 0))
        self.counters["blocks"] += blocks
        self.counters["blocks_shipped"] += shipped
        if blocks and shipped < blocks:
            self.counters["warm_frames"] += 1
        led = self.member_ledger.setdefault(
            member or "decode",
            {"frames": 0, "bytes": 0, "blocks": 0, "blocks_shipped": 0,
             "warm_frames": 0})
        led["frames"] += 1
        led["bytes"] += nbytes
        led["blocks"] += blocks
        led["blocks_shipped"] += shipped
        if blocks and shipped < blocks:
            led["warm_frames"] += 1
        if p == 0:
            self.counters["routing_only"] += 1
        op: dict[str, Any] = {"op": HostOp.ADOPT, "id": req_id,
                              "frame": handoff.get("frame")}
        for k in ("max_new", "sampling", "speculative", "trace", "resume"):
            # "resume" rides through so the decode tier restores the
            # RNG lane and token budget of a resumed request (the
            # emitted tokens themselves already ride the frame — the
            # prefill tier appended them to the prompt).
            if k in keep:
                op[k] = keep[k]
        if "deadline_s" in keep:
            # The deadline was RELATIVE at provider submit; the prefill
            # tier consumed part of it. Rebase so the decode host's
            # admission shed still fires at the original wall deadline.
            op["deadline_s"] = float(keep["deadline_s"]) - (now - t_submit)
        return op

    # -------------------------------------------------------------- stats

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = dict(self.counters)
        out["wire_s_total"] = round(out["wire_s_total"], 4)
        out["pending"] = len(self._pending)
        out["prefill_tier_s"] = self.prefill_tier_hist.to_dict()
        out["wire_s"] = self.wire_hist.to_dict()
        if self.member_ledger:
            out["per_member"] = {m: dict(v)
                                 for m, v in sorted(
                                     self.member_ledger.items())}
        return out
