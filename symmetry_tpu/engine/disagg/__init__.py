"""Disaggregated prefill/decode: KV-handoff frames + the tier broker.

The production-serving split (DistServe OSDI'24, Splitwise ISCA'24, see
PAPERS.md): admissions/chunked prefill on one engine host, generation on
another, with each finished prompt's KV crossing the boundary as a
versioned binary frame the decode tier adopts through its prefix store.
`tpu.role` selects a host's tier; `tpu.role: disagg` makes the
tpu_native backend run the pair under one supervisor.
"""

from symmetry_tpu.engine.disagg.broker import (
    DEFAULT_DECODE_PREFIX_MB,
    HandoffBroker,
    derive_role_config,
)
from symmetry_tpu.engine.disagg.frames import (
    FrameError,
    KVHandoff,
    decode_frame,
    decode_kv_handoff,
    encode_frame,
    encode_kv_handoff,
)

__all__ = [
    "DEFAULT_DECODE_PREFIX_MB",
    "FrameError",
    "HandoffBroker",
    "KVHandoff",
    "decode_frame",
    "decode_kv_handoff",
    "derive_role_config",
    "encode_frame",
    "encode_kv_handoff",
]
