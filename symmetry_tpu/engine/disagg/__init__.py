"""Disaggregated prefill/decode: KV-handoff frames + the tier broker.

The production-serving split (DistServe OSDI'24, Splitwise ISCA'24, see
PAPERS.md): admissions/chunked prefill on one engine host, generation on
another, with each finished prompt's KV crossing the boundary as a
versioned binary frame the decode tier adopts through its prefix store.
`tpu.role` selects a host's tier; `tpu.role: disagg` makes the
tpu_native backend run the pair under one supervisor.

Cross-machine: `tpu.disagg.peer` switches the backend to NETWORK mode —
the decode tier stays local and the prefill tier runs on another
machine as an engine/disagg/node.py PrefillNode, the two joined by the
chunked, credit-flow-controlled, acked handoff link in
engine/disagg/net.py over the transport/ stack (MemoryTransport in
tests, TCP in production, optional Noise encryption).
"""

from symmetry_tpu.engine.disagg.autoscale import (
    AutoscaleConfig,
    PoolAutoscaler,
)
from symmetry_tpu.engine.disagg.broker import (
    DEFAULT_DECODE_PREFIX_MB,
    HandoffBroker,
    derive_role_config,
)
from symmetry_tpu.engine.disagg.frames import (
    FrameError,
    KVHandoff,
    decode_frame,
    decode_kv_handoff,
    encode_frame,
    encode_kv_handoff,
)
from symmetry_tpu.engine.disagg.net import (
    DecodeLink,
    LinkConfig,
    LinkError,
)
from symmetry_tpu.engine.disagg.pool import (
    MemberState,
    PoolConfig,
    PoolRouter,
)

__all__ = [
    "AutoscaleConfig",
    "DEFAULT_DECODE_PREFIX_MB",
    "DecodeLink",
    "PoolAutoscaler",
    "FrameError",
    "HandoffBroker",
    "KVHandoff",
    "LinkConfig",
    "LinkError",
    "MemberState",
    "PoolConfig",
    "PoolRouter",
    "decode_frame",
    "decode_kv_handoff",
    "derive_role_config",
    "encode_frame",
    "encode_kv_handoff",
]
