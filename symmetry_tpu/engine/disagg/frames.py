"""Versioned, length-prefixed KV-handoff frames.

The wire unit of disaggregated prefill/decode: the prefill tier
serializes a finished request's prefix KV (and, same format, a
prefix-cache entry's KV) into ONE self-describing binary frame; the
decode tier deserializes it and adopts the blocks into its radix tree
(`engine.adopt_prefix`). The format is deliberately dumb and
explicit — a handoff crosses process (and eventually chip/host)
boundaries, so every field that could silently corrupt a decode stream
is checked at parse time instead of trusted:

    magic   b"SYKV"                      wrong stream → FrameError
    u16     version (=2)                 unknown layout → FrameError
    u16     flags (bit 0: int8 KV)       quantization mismatch is loud
    u64     body length                  truncation → FrameError
    body    u32 header-JSON length, header JSON (meta: request id,
            prompt tokens, prefix length p, block size, the per-block
            digest MANIFEST, which block indices ship …), u16 array
            count, then per array: name, dtype name, shape, u64 payload
            length, raw row-major bytes
    u32     crc32(body)                  bit rot / torn write → FrameError

Version 2 (the radix/paged-KV round) makes the payload BLOCK-GRANULAR:
the prefix is cut into fixed-size token blocks, each block ships as its
own named arrays ("k:3", "v:3", …), and the meta carries a digest per
block (over the block's full causal token context). The sender may
OMIT blocks it has already shipped to this tier — the receiver adopts
omitted blocks by reference when its radix tree still holds them, or
shortens the adopted prefix when it doesn't (always causally sound).
That is what turns a warm multi-turn handoff from a full-prefix copy
into a few tail blocks on the wire. Version-1 frames (monolithic
slabs, no manifest) are REJECTED loudly, as any unknown version is.

Arrays are GQA-shaped per block ([layers, 1, bs, kv_heads, head_dim]
payloads; [layers, 1, kv_heads, bs] scale planes when the KV cache is
int8-quantized) but the codec itself is shape-agnostic — it round-trips
whatever named arrays it is given, so the same frames carry bf16/f32
caches, quantized caches, and future layouts without a version bump as
long as the meta describes them.

Host byte order is little-endian on every platform this runs on (x86,
TPU hosts, arm64); the format pins little-endian explicitly so a frame
written on one host parses on any other.
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

MAGIC = b"SYKV"
VERSION = 2
FLAG_KV_INT8 = 1 << 0

# A frame is one request's prefix KV: even a 70B-scale cache slice is
# hundreds of MB, not GB. The bound exists so a corrupt length prefix
# fails parsing instead of driving a multi-GB allocation.
MAX_FRAME_BYTES = 4 << 30


class FrameError(ValueError):
    """Rejected handoff frame: truncated, corrupt, or wrong version."""


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its serialized name, including the ml_dtypes extras
    (bfloat16 …) numpy cannot resolve by string."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    import ml_dtypes

    dt = getattr(ml_dtypes, name, None)
    if dt is None:
        raise FrameError(f"unknown array dtype {name!r} in handoff frame")
    return np.dtype(dt)


def encode_frame(meta: dict, arrays: dict[str, np.ndarray],
                 *, flags: int = 0) -> bytes:
    """One meta dict + named arrays → a self-contained frame. `meta`
    must be JSON-serializable; arrays are written C-contiguous."""
    header = json.dumps(meta, separators=(",", ":")).encode()
    parts = [struct.pack("<I", len(header)), header,
             struct.pack("<H", len(arrays))]
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        name_b = name.encode()
        dtype_b = arr.dtype.name.encode()
        parts.append(struct.pack("<H", len(name_b)))
        parts.append(name_b)
        parts.append(struct.pack("<H", len(dtype_b)))
        parts.append(dtype_b)
        parts.append(struct.pack("<H", arr.ndim))
        parts.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
        data = arr.tobytes()
        parts.append(struct.pack("<Q", len(data)))
        parts.append(data)
    body = b"".join(parts)
    return b"".join([MAGIC, struct.pack("<HH", VERSION, flags),
                     struct.pack("<Q", len(body)), body,
                     struct.pack("<I", zlib.crc32(body))])


def decode_frame(buf: bytes) -> tuple[dict, dict[str, np.ndarray], int]:
    """Parse one frame → (meta, arrays, flags). Every structural check
    raises FrameError — a rejected frame must fail THIS request loudly,
    never adopt garbage KV into a live decode host."""
    if len(buf) < 16:
        raise FrameError(f"frame truncated: {len(buf)} bytes < 16-byte "
                         f"fixed header")
    if buf[:4] != MAGIC:
        raise FrameError(f"bad frame magic {buf[:4]!r}")
    version, flags = struct.unpack_from("<HH", buf, 4)
    if version != VERSION:
        raise FrameError(f"unsupported handoff frame version {version} "
                         f"(this build speaks {VERSION})")
    (body_len,) = struct.unpack_from("<Q", buf, 8)
    if body_len > MAX_FRAME_BYTES:
        raise FrameError(f"frame body length {body_len} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte bound")
    if len(buf) != 16 + body_len + 4:
        raise FrameError(f"frame truncated: have {len(buf)} bytes, "
                         f"header promises {16 + body_len + 4}")
    body = buf[16:16 + body_len]
    (crc,) = struct.unpack_from("<I", buf, 16 + body_len)
    if zlib.crc32(body) != crc:
        raise FrameError("frame checksum mismatch (corrupt payload)")

    off = 0

    def take(n: int, what: str) -> bytes:
        nonlocal off
        if off + n > len(body):
            raise FrameError(f"frame body truncated reading {what}")
        out = body[off:off + n]
        off += n
        return out

    (header_len,) = struct.unpack("<I", take(4, "header length"))
    try:
        meta = json.loads(take(header_len, "header"))
    except ValueError as exc:
        raise FrameError(f"frame header is not valid JSON: {exc}") from exc
    if not isinstance(meta, dict):
        raise FrameError("frame header must be a JSON object")
    (n_arrays,) = struct.unpack("<H", take(2, "array count"))
    arrays: dict[str, np.ndarray] = {}
    for _ in range(n_arrays):
        (name_len,) = struct.unpack("<H", take(2, "array name length"))
        name = take(name_len, "array name").decode()
        (dtype_len,) = struct.unpack("<H", take(2, "dtype length"))
        dtype = _np_dtype(take(dtype_len, "dtype name").decode())
        (ndim,) = struct.unpack("<H", take(2, "rank"))
        shape = struct.unpack(f"<{ndim}I", take(4 * ndim, "shape"))
        (data_len,) = struct.unpack("<Q", take(8, "payload length"))
        want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if data_len != want:
            raise FrameError(
                f"array {name!r} payload is {data_len} bytes but shape "
                f"{shape} × {dtype.name} needs {want}")
        data = take(data_len, f"array {name!r} payload")
        arrays[name] = np.frombuffer(data, dtype=dtype).reshape(shape)
    if off != len(body):
        raise FrameError(f"{len(body) - off} trailing bytes after the "
                         f"last array")
    return meta, arrays, flags


# ---------------------------------------------------------------------
# The KV-handoff frame: the per-request block-granular payload the
# prefill tier ships to the decode tier.

_PLANE_KEYS = ("k", "v", "k_scale", "v_scale")
_PLANE_WIRE = {"k": "k", "v": "v", "k_scale": "ks", "v_scale": "vs"}
_WIRE_PLANE = {v: k for k, v in _PLANE_WIRE.items()}


@dataclass
class KVHandoff:
    """One decoded handoff: the full prompt's token ids, the prefix
    length `p` the manifest covers, the block size, the per-block
    digest manifest, and the GQA-shaped payloads of the blocks that
    actually shipped (a subset — the sender omits blocks it already
    shipped to this tier; `blocks` is empty when p == 0, the
    routing-only frame for prompts too short to hand off)."""

    request_id: str
    tokens: tuple[int, ...]        # FULL prompt (manifest covers [:p])
    p: int                         # prefix length covered by the manifest
    block_size: int = 0            # tokens per block (p // bs blocks)
    kv_quant: bool = False
    digests: tuple[str, ...] = ()  # hex digest per block, causal context
    # block index -> {"k", "v"[, "k_scale", "v_scale"]} per-block planes
    blocks: dict[int, dict[str, np.ndarray]] = field(default_factory=dict)

    @property
    def n_blocks(self) -> int:
        return self.p // self.block_size if self.block_size else 0

    @property
    def shipped(self) -> list[int]:
        return sorted(self.blocks)

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for planes in self.blocks.values()
                   for a in planes.values())

    @property
    def arrays(self) -> dict[str, np.ndarray]:
        """Whole-prefix planes reassembled from the blocks — only valid
        when EVERY block shipped (tests and debugging; the engine adopts
        block-wise). Raises on a partial frame."""
        if self.p == 0:
            return {}
        if set(self.blocks) != set(range(self.n_blocks)):
            raise FrameError(
                f"cannot reassemble whole-prefix arrays: blocks "
                f"{self.shipped} of {self.n_blocks} shipped")
        out: dict[str, np.ndarray] = {}
        for key in _PLANE_KEYS:
            if key not in self.blocks[0]:
                continue
            axis = 3 if key.endswith("_scale") else 2
            out[key] = np.concatenate(
                [self.blocks[j][key] for j in range(self.n_blocks)],
                axis=axis)
        return out


def encode_kv_handoff(request_id: str, tokens, p: int,
                      arrays: dict[str, np.ndarray] | None,
                      *, kv_quant: bool = False, block_size: int = 0,
                      skip=(), digests: list[str] | None = None) -> bytes:
    """Serialize one request's prefix KV slice, blockwise. `arrays`
    holds the batch-1 cache planes sliced to `p` positions (k/v
    payloads, plus k_scale/v_scale when int8-quantized) — the codec
    cuts them into `block_size`-token blocks (0 → one block spanning
    the whole prefix) and ships each block as its own named arrays.
    `skip` names block indices to OMIT from the payload (already
    shipped to this tier — the receiver adopts them by reference or
    shortens the prefix); every block still appears in the digest
    manifest. A caller that already computed the manifest (the host's
    shipped-block ledger) passes it via `digests` instead of paying the
    hash twice. None/{} with p == 0 is the routing-only frame for
    prompts with no whole-block prefix."""
    from symmetry_tpu.engine.prefix_cache import block_digests

    arrays = arrays or {}
    if p < 0 or p > len(tokens):
        raise ValueError(f"prefix length {p} outside prompt of "
                         f"{len(tokens)} tokens")
    if p == 0 and arrays:
        raise ValueError("p == 0 handoff must carry no KV arrays")
    bs = int(block_size) or int(p)
    out_arrays: dict[str, np.ndarray] = {}
    skip = set(skip)
    if p <= 0:
        digests = []
    if p > 0:
        missing = {"k", "v"} - set(arrays)
        if kv_quant:
            missing |= {"k_scale", "v_scale"} - set(arrays)
        if missing:
            raise ValueError(f"handoff missing KV planes: {sorted(missing)}")
        if bs < 1 or p % bs:
            raise ValueError(f"prefix length {p} is not a multiple of "
                             f"block size {bs}")
        if digests is None:
            digests = block_digests(list(tokens), p, bs)
        elif len(digests) != p // bs:
            raise ValueError(f"caller-supplied manifest has {len(digests)} "
                             f"digests for {p // bs} blocks")
        if not skip <= set(range(p // bs)):
            raise ValueError(f"skip indices {sorted(skip)} outside the "
                             f"{p // bs}-block manifest")
        for j in range(p // bs):
            if j in skip:
                continue
            for key, wire in _PLANE_WIRE.items():
                if key not in arrays:
                    continue
                axis = 3 if key.endswith("_scale") else 2
                sl = [slice(None)] * arrays[key].ndim
                sl[axis] = slice(j * bs, (j + 1) * bs)
                out_arrays[f"{wire}:{j}"] = arrays[key][tuple(sl)]
    meta = {"id": str(request_id), "tokens": list(map(int, tokens)),
            "p": int(p), "kv_quant": bool(kv_quant), "bs": bs,
            "digests": digests,
            "shipped": sorted(set(range(p // bs)) - skip) if p else []}
    return encode_frame(meta, out_arrays,
                        flags=FLAG_KV_INT8 if kv_quant else 0)


def decode_kv_handoff(buf: bytes) -> KVHandoff:
    """Parse + validate one handoff frame. Structural KV checks (shapes
    against the decode engine's model config, block size against its
    pool) belong to the adopting engine — this layer only guarantees
    the frame is internally consistent."""
    meta, arrays, flags = decode_frame(buf)
    try:
        tokens = tuple(int(t) for t in meta["tokens"])
        p = int(meta["p"])
        req_id = str(meta["id"])
        bs = int(meta.get("bs", p))
        digests = tuple(str(d) for d in meta.get("digests", ()))
        shipped = [int(j) for j in meta.get("shipped", ())]
    except (KeyError, TypeError, ValueError) as exc:
        raise FrameError(f"handoff meta malformed: {exc!r}") from exc
    kv_quant = bool(meta.get("kv_quant", False))
    if kv_quant != bool(flags & FLAG_KV_INT8):
        raise FrameError("handoff flags disagree with meta on KV "
                         "quantization")
    if not 0 <= p <= len(tokens):
        raise FrameError(f"handoff prefix length {p} outside prompt of "
                         f"{len(tokens)} tokens")
    if p == 0:
        if arrays:
            raise FrameError("p == 0 handoff carries KV arrays")
        return KVHandoff(request_id=req_id, tokens=tokens, p=0)
    if bs < 1 or p % bs:
        raise FrameError(f"handoff prefix length {p} is not a multiple "
                         f"of its block size {bs}")
    n_blocks = p // bs
    if len(digests) != n_blocks:
        raise FrameError(f"handoff manifest has {len(digests)} digests "
                         f"for {n_blocks} blocks")
    shipped_set = set(shipped)
    if not shipped_set <= set(range(n_blocks)):
        raise FrameError(f"handoff ships blocks {shipped} outside the "
                         f"{n_blocks}-block manifest")
    want_planes = {"k", "v"} | ({"k_scale", "v_scale"} if kv_quant
                                else set())
    blocks: dict[int, dict[str, np.ndarray]] = {}
    for name, arr in arrays.items():
        wire, _, idx = name.partition(":")
        key = _WIRE_PLANE.get(wire)
        if key is None or not idx.isdigit():
            raise FrameError(f"unknown handoff array {name!r}")
        j = int(idx)
        if j not in shipped_set:
            raise FrameError(f"handoff array {name!r} for a block the "
                             f"manifest says was not shipped")
        blocks.setdefault(j, {})[key] = arr
    if set(blocks) != shipped_set:
        raise FrameError(f"handoff shipped-block payloads {sorted(blocks)} "
                         f"disagree with manifest {sorted(shipped_set)}")
    for j, planes in blocks.items():
        if set(planes) != want_planes:
            raise FrameError(
                f"handoff block {j} planes {sorted(planes)} != expected "
                f"{sorted(want_planes)}")
        for name in ("k", "v"):
            a = planes[name]
            if a.ndim != 5 or a.shape[1] != 1 or a.shape[2] != bs:
                raise FrameError(
                    f"handoff block {j} {name} shape {a.shape} is not "
                    f"[layers, 1, bs={bs}, kv_heads, head_dim]")
        if planes["k"].shape != planes["v"].shape:
            raise FrameError(f"handoff block {j} k/v shapes disagree")
        if kv_quant:
            for name in ("k_scale", "v_scale"):
                a = planes[name]
                if a.ndim != 4 or a.shape[1] != 1 or a.shape[3] != bs:
                    raise FrameError(
                        f"handoff block {j} {name} shape {a.shape} is "
                        f"not [layers, 1, kv_heads, bs={bs}]")
    return KVHandoff(request_id=req_id, tokens=tokens, p=p,
                     block_size=bs, kv_quant=kv_quant, digests=digests,
                     blocks=blocks)
