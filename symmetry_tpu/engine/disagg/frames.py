"""Versioned, length-prefixed KV-handoff frames.

The wire unit of disaggregated prefill/decode: the prefill tier
serializes a finished request's prefix KV (and, same format, a
prefix-cache entry's KV) into ONE self-describing binary frame; the
decode tier deserializes it and adopts the buffers through the
`PrefixStore.insert` seed-copy path. The format is deliberately dumb and
explicit — a handoff crosses process (and eventually chip/host)
boundaries, so every field that could silently corrupt a decode stream
is checked at parse time instead of trusted:

    magic   b"SYKV"                      wrong stream → FrameError
    u16     version (=1)                 unknown layout → FrameError
    u16     flags (bit 0: int8 KV)       quantization mismatch is loud
    u64     body length                  truncation → FrameError
    body    u32 header-JSON length, header JSON (meta: request id,
            prompt tokens, prefix length p, dtype names …), u16 array
            count, then per array: name, dtype name, shape, u64 payload
            length, raw row-major bytes
    u32     crc32(body)                  bit rot / torn write → FrameError

Arrays are GQA-shaped as stored ([layers, 1, p, kv_heads, head_dim]
payloads; [layers, 1, kv_heads, p] scale planes when the KV cache is
int8-quantized) but the codec itself is shape-agnostic — it round-trips
whatever named arrays it is given, so the same frames carry bf16/f32
caches, quantized caches, and future layouts without a version bump as
long as the meta describes them.

Host byte order is little-endian on every platform this runs on (x86,
TPU hosts, arm64); the format pins little-endian explicitly so a frame
written on one host parses on any other.
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

MAGIC = b"SYKV"
VERSION = 1
FLAG_KV_INT8 = 1 << 0

# A frame is one request's prefix KV: even a 70B-scale cache slice is
# hundreds of MB, not GB. The bound exists so a corrupt length prefix
# fails parsing instead of driving a multi-GB allocation.
MAX_FRAME_BYTES = 4 << 30


class FrameError(ValueError):
    """Rejected handoff frame: truncated, corrupt, or wrong version."""


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its serialized name, including the ml_dtypes extras
    (bfloat16 …) numpy cannot resolve by string."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    import ml_dtypes

    dt = getattr(ml_dtypes, name, None)
    if dt is None:
        raise FrameError(f"unknown array dtype {name!r} in handoff frame")
    return np.dtype(dt)


def encode_frame(meta: dict, arrays: dict[str, np.ndarray],
                 *, flags: int = 0) -> bytes:
    """One meta dict + named arrays → a self-contained frame. `meta`
    must be JSON-serializable; arrays are written C-contiguous."""
    header = json.dumps(meta, separators=(",", ":")).encode()
    parts = [struct.pack("<I", len(header)), header,
             struct.pack("<H", len(arrays))]
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        name_b = name.encode()
        dtype_b = arr.dtype.name.encode()
        parts.append(struct.pack("<H", len(name_b)))
        parts.append(name_b)
        parts.append(struct.pack("<H", len(dtype_b)))
        parts.append(dtype_b)
        parts.append(struct.pack("<H", arr.ndim))
        parts.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
        data = arr.tobytes()
        parts.append(struct.pack("<Q", len(data)))
        parts.append(data)
    body = b"".join(parts)
    return b"".join([MAGIC, struct.pack("<HH", VERSION, flags),
                     struct.pack("<Q", len(body)), body,
                     struct.pack("<I", zlib.crc32(body))])


def decode_frame(buf: bytes) -> tuple[dict, dict[str, np.ndarray], int]:
    """Parse one frame → (meta, arrays, flags). Every structural check
    raises FrameError — a rejected frame must fail THIS request loudly,
    never adopt garbage KV into a live decode host."""
    if len(buf) < 16:
        raise FrameError(f"frame truncated: {len(buf)} bytes < 16-byte "
                         f"fixed header")
    if buf[:4] != MAGIC:
        raise FrameError(f"bad frame magic {buf[:4]!r}")
    version, flags = struct.unpack_from("<HH", buf, 4)
    if version != VERSION:
        raise FrameError(f"unsupported handoff frame version {version} "
                         f"(this build speaks {VERSION})")
    (body_len,) = struct.unpack_from("<Q", buf, 8)
    if body_len > MAX_FRAME_BYTES:
        raise FrameError(f"frame body length {body_len} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte bound")
    if len(buf) != 16 + body_len + 4:
        raise FrameError(f"frame truncated: have {len(buf)} bytes, "
                         f"header promises {16 + body_len + 4}")
    body = buf[16:16 + body_len]
    (crc,) = struct.unpack_from("<I", buf, 16 + body_len)
    if zlib.crc32(body) != crc:
        raise FrameError("frame checksum mismatch (corrupt payload)")

    off = 0

    def take(n: int, what: str) -> bytes:
        nonlocal off
        if off + n > len(body):
            raise FrameError(f"frame body truncated reading {what}")
        out = body[off:off + n]
        off += n
        return out

    (header_len,) = struct.unpack("<I", take(4, "header length"))
    try:
        meta = json.loads(take(header_len, "header"))
    except ValueError as exc:
        raise FrameError(f"frame header is not valid JSON: {exc}") from exc
    if not isinstance(meta, dict):
        raise FrameError("frame header must be a JSON object")
    (n_arrays,) = struct.unpack("<H", take(2, "array count"))
    arrays: dict[str, np.ndarray] = {}
    for _ in range(n_arrays):
        (name_len,) = struct.unpack("<H", take(2, "array name length"))
        name = take(name_len, "array name").decode()
        (dtype_len,) = struct.unpack("<H", take(2, "dtype length"))
        dtype = _np_dtype(take(dtype_len, "dtype name").decode())
        (ndim,) = struct.unpack("<H", take(2, "rank"))
        shape = struct.unpack(f"<{ndim}I", take(4 * ndim, "shape"))
        (data_len,) = struct.unpack("<Q", take(8, "payload length"))
        want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if data_len != want:
            raise FrameError(
                f"array {name!r} payload is {data_len} bytes but shape "
                f"{shape} × {dtype.name} needs {want}")
        data = take(data_len, f"array {name!r} payload")
        arrays[name] = np.frombuffer(data, dtype=dtype).reshape(shape)
    if off != len(body):
        raise FrameError(f"{len(body) - off} trailing bytes after the "
                         f"last array")
    return meta, arrays, flags


# ---------------------------------------------------------------------
# The KV-handoff frame: the per-request (or prefix-cache-entry) payload
# the prefill tier ships to the decode tier.

@dataclass
class KVHandoff:
    """One decoded handoff: the full prompt's token ids, the aligned
    prefix length `p` whose KV the arrays carry, and the GQA-shaped
    buffers themselves (empty when p == 0 — a prompt too short for an
    aligned prefix hands off routing-only and the decode tier prefills
    it whole)."""

    request_id: str
    tokens: tuple[int, ...]        # FULL prompt (frame covers [:p])
    p: int                         # aligned prefix length serialized
    kv_quant: bool = False
    arrays: dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays.values())


def encode_kv_handoff(request_id: str, tokens, p: int,
                      arrays: dict[str, np.ndarray] | None,
                      *, kv_quant: bool = False) -> bytes:
    """Serialize one request's prefix KV slice. `arrays` holds the
    batch-1 cache planes sliced to `p` positions (k/v payloads, plus
    k_scale/v_scale when int8-quantized); None/{} with p == 0 is the
    routing-only frame for prompts with no aligned prefix."""
    arrays = arrays or {}
    if p < 0 or p > len(tokens):
        raise ValueError(f"prefix length {p} outside prompt of "
                         f"{len(tokens)} tokens")
    if p == 0 and arrays:
        raise ValueError("p == 0 handoff must carry no KV arrays")
    if p > 0:
        missing = {"k", "v"} - set(arrays)
        if kv_quant:
            missing |= {"k_scale", "v_scale"} - set(arrays)
        if missing:
            raise ValueError(f"handoff missing KV planes: {sorted(missing)}")
    meta = {"id": str(request_id), "tokens": list(map(int, tokens)),
            "p": int(p), "kv_quant": bool(kv_quant)}
    return encode_frame(meta, arrays,
                        flags=FLAG_KV_INT8 if kv_quant else 0)


def decode_kv_handoff(buf: bytes) -> KVHandoff:
    """Parse + validate one handoff frame. Structural KV checks (shapes
    against the decode engine's model config, alignment against its
    prefix store) belong to the adopting engine — this layer only
    guarantees the frame is internally consistent."""
    meta, arrays, flags = decode_frame(buf)
    try:
        tokens = tuple(int(t) for t in meta["tokens"])
        p = int(meta["p"])
        req_id = str(meta["id"])
    except (KeyError, TypeError, ValueError) as exc:
        raise FrameError(f"handoff meta malformed: {exc!r}") from exc
    kv_quant = bool(meta.get("kv_quant", False))
    if kv_quant != bool(flags & FLAG_KV_INT8):
        raise FrameError("handoff flags disagree with meta on KV "
                         "quantization")
    if not 0 <= p <= len(tokens):
        raise FrameError(f"handoff prefix length {p} outside prompt of "
                         f"{len(tokens)} tokens")
    if p == 0:
        if arrays:
            raise FrameError("p == 0 handoff carries KV arrays")
    else:
        want = {"k", "v"} | ({"k_scale", "v_scale"} if kv_quant else set())
        if set(arrays) != want:
            raise FrameError(
                f"handoff arrays {sorted(arrays)} != expected "
                f"{sorted(want)}")
        for name in ("k", "v"):
            a = arrays[name]
            if a.ndim != 5 or a.shape[1] != 1 or a.shape[2] != p:
                raise FrameError(
                    f"handoff {name} shape {a.shape} is not "
                    f"[layers, 1, p={p}, kv_heads, head_dim]")
        if arrays["k"].shape != arrays["v"].shape:
            raise FrameError("handoff k/v shapes disagree")
        if kv_quant:
            for name in ("k_scale", "v_scale"):
                a = arrays[name]
                if a.ndim != 4 or a.shape[1] != 1 or a.shape[3] != p:
                    raise FrameError(
                        f"handoff {name} shape {a.shape} is not "
                        f"[layers, 1, kv_heads, p={p}]")
    return KVHandoff(request_id=req_id, tokens=tokens, p=p,
                     kv_quant=kv_quant, arrays=arrays)
