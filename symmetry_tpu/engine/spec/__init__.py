"""Speculative decoding subsystem (off by default; `tpu.speculative`).

Three pieces, one per layer of the serving stack:

  - drafter.py (host): per-slot n-gram prompt-lookup index proposing up
    to k_draft continuation tokens per slot per block — no draft model.
  - ops/sampling.py verify_tokens (device): per-position acceptance
    against the target distribution — exact for greedy lanes, unbiased
    rejection sampling for temperature/top-p/top-k lanes.
  - engine.py verify_step + scheduler integration: ONE batched
    [B, 1 + k_draft] forward verifies every slot's proposals, rolls each
    slot's cache length back to its first rejection, and the scheduler
    emits the variable-length accepted spans through the existing
    block-granular event frames.
"""

from symmetry_tpu.engine.spec.drafter import NGramDrafter, SpecConfig

__all__ = ["NGramDrafter", "SpecConfig"]
