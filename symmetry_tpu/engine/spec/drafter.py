"""Host-side n-gram prompt-lookup drafter (speculative decoding, draft half).

Prompt-lookup decoding (Saxena; PAPERS.md) drafts continuation tokens with
NO draft model: serving workloads that repeat long spans of their own
context — code edits, RAG answers quoting retrieved passages, extractive
summaries, chat turns restating a preamble — let the last few generated
tokens be matched against an index of the slot's prompt + generation so
far, and the tokens that followed the previous occurrence become the
proposal. The engine's verify pass (engine.verify_step / ops/sampling.
verify_tokens) then scores all proposals in ONE batched forward and keeps
the longest target-agreeing prefix, so a wrong proposal costs one wasted
lane position, never a wrong token.

Everything here is plain host Python on small lists — no JAX, no device
work — mirroring how StreamDecoder keeps detokenizer state host-side. The
scheduler owns one drafter and drives begin/extend/propose/release around
its decode loop; the index is per-slot and dies with the slot.

Matching rule (per slot): try the longest context suffix first
(`ngram_max` down to `ngram_min` tokens), look up a prior occurrence,
and propose up to `k_draft` tokens that followed it. The index keeps the
last few occurrence positions per n-gram, newest first, because (a) the
current context suffix is itself always the newest entry — a draft must
continue a STRICTLY EARLIER occurrence — and (b) near-tail occurrences
have their continuation truncated by the tail itself (a period-1 loop's
newest prior match yields a 1-token draft), so the proposer prefers the
newest occurrence old enough to supply all k_draft tokens.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable


@dataclass(frozen=True)
class SpecConfig:
    """`tpu.speculative` knob, parsed. k_draft is the verify lane width
    (draft tokens per slot per dispatch); the n-gram bounds trade match
    precision (longer = fewer, better matches) against coverage."""

    k_draft: int = 8
    ngram_max: int = 3
    ngram_min: int = 1
    # Prompt positions indexed at slot admission (begin() runs on the
    # scheduler's single serving thread, so its cost stalls every active
    # stream): prompts longer than this index only their LAST
    # max_index_tokens — recent context matches matter most, and
    # generation keeps extending the indexed tail incrementally.
    max_index_tokens: int = 4096

    def __post_init__(self) -> None:
        if self.k_draft < 1:
            raise ValueError("speculative k_draft must be >= 1")
        if not 1 <= self.ngram_min <= self.ngram_max:
            raise ValueError("speculative needs 1 <= ngram_min <= ngram_max")
        if self.max_index_tokens < self.ngram_max + self.k_draft:
            raise ValueError("speculative max_index_tokens too small")

    @classmethod
    def from_knob(cls, knob: Any) -> "SpecConfig | None":
        """Parse the `tpu.speculative` config value: falsy disables;
        True = defaults; an int = k_draft; a mapping = field overrides."""
        if not knob:
            return None
        if knob is True:
            return cls()
        if isinstance(knob, int):
            return cls(k_draft=knob)
        if isinstance(knob, dict):
            unknown = set(knob) - {"k_draft", "ngram_max", "ngram_min",
                                   "max_index_tokens"}
            if unknown:
                raise ValueError(
                    f"unknown tpu.speculative keys: {sorted(unknown)}")
            return cls(**{k: int(v) for k, v in knob.items()})
        raise ValueError(
            f"tpu.speculative must be a bool, int, or mapping, "
            f"got {type(knob).__name__}")


class NGramDrafter:
    """Per-slot prompt-lookup index + proposal generation.

    Not thread-safe; lives on the scheduler's engine thread like every
    other piece of per-slot host state.
    """

    def __init__(self, config: SpecConfig) -> None:
        self.config = config
        # slot -> full token context (prompt + emitted generation)
        self._ctx: dict[int, list[int]] = {}
        # slot -> {ngram tuple: occurrence ends, NEWEST FIRST} — an "end"
        # is the context position right AFTER the n-gram, i.e. where its
        # continuation starts. Bounded per key: k_draft + 1 entries
        # guarantee that even a period-1 token loop (whose newest
        # occurrences all sit inside the tail) retains one occurrence at
        # least k_draft tokens back, so propose() can emit a full draft.
        self._index: dict[int, dict[tuple[int, ...], list[int]]] = {}
        self._hist = config.k_draft + 1

    # ------------------------------------------------------------- lifecycle

    def begin(self, slot: int, prompt_ids: Iterable[int],
              first_token: int) -> None:
        """Install a freshly-activated slot: context = prompt + the first
        sampled token (decode continues from it). Indexing runs on the
        scheduler's serving thread where a stall holds every active
        stream, so only the last max_index_tokens of a long prompt are
        indexed — matches against the dropped head are forfeited, the
        admission cost stays bounded."""
        ctx = list(prompt_ids)[-self.config.max_index_tokens:]
        ctx.append(first_token)
        self._ctx[slot] = []
        self._index[slot] = {}
        self.extend(slot, ctx)

    def extend(self, slot: int, tokens: Iterable[int]) -> None:
        """Append emitted tokens to the slot's context and index every
        n-gram they complete. Called once per processed block — O(block ×
        n-gram range) dict writes, no scans."""
        ctx = self._ctx.get(slot)
        if ctx is None:
            return
        index = self._index[slot]
        cfg = self.config
        for tok in tokens:
            ctx.append(int(tok))
            end = len(ctx)
            for n in range(cfg.ngram_min, cfg.ngram_max + 1):
                if end < n:
                    continue
                key = tuple(ctx[end - n:end])
                ends = index.get(key)
                if ends is None:
                    index[key] = [end]
                else:
                    ends.insert(0, end)
                    del ends[self._hist:]

    def release(self, slot: int) -> None:
        self._ctx.pop(slot, None)
        self._index.pop(slot, None)

    def active_slots(self) -> list[int]:
        return list(self._ctx)

    # ------------------------------------------------------------- proposals

    def propose(self, slot: int) -> list[int]:
        """Up to k_draft continuation tokens for `slot`, or [] when no
        context suffix recurs (the slot then rides a plain decode lane)."""
        ctx = self._ctx.get(slot)
        if not ctx:
            return []
        index = self._index[slot]
        cfg = self.config
        end = len(ctx)
        for n in range(min(cfg.ngram_max, end), cfg.ngram_min - 1, -1):
            ends = index.get(tuple(ctx[end - n:end]))
            if ends is None:
                continue
            # Newest occurrence old enough to supply a FULL draft; else
            # the newest strictly-prior one (short draft beats none). The
            # newest entry is the context's own tail (start == end).
            best: int | None = None
            for start in ends:
                if start >= end:
                    continue
                if best is None:
                    best = start
                if start + cfg.k_draft <= end:
                    best = start
                    break
            if best is None:
                continue
            return ctx[best:best + cfg.k_draft]
        return []
