"""Shared-prefix KV store: prefill each common prefix once, admit many.

Realistic serving traffic overwhelmingly shares prompt prefixes — system
prompts, few-shot preambles, multi-turn history — yet every request used
to pay a full prefill. This module is the host-side bookkeeping for
automatic prefix reuse (the engine owns the device work): an HBM-budgeted
LRU of batch-1 prefix `KVCache` buffers, keyed by the token content of
ALIGNED prompt prefixes, in the spirit of vLLM's automatic prefix caching
and SGLang's RadixAttention but shaped for this engine's static-bucket
world.

Design points:

  - Alignment. Prefixes are stored and matched only at multiples of the
    engine's `prefix_align` (min(prefill_chunk, smallest bucket)): the
    hit path runs the uncached suffix through ONE fixed-shape
    continuation dispatch, so the suffix must fit a compiled shape. A
    stored entry of aligned length P serves a hit at ANY aligned p <= P
    — KV at position i depends only on tokens <= i (causal), so the
    first p positions of a longer prefix ARE the shorter prefix's KV.
    The index therefore maps every aligned boundary of every entry.

  - Keys are digests of the prefix token bytes; a hit re-verifies the
    actual tokens against the entry (collisions must produce a miss,
    never silently wrong KV).

  - Strictly-partial matches only: lookup never returns p == len(prompt).
    The suffix (>= 1 token) is what produces the first sampled token —
    the continuation dispatch projects the last valid position and
    samples, so a "full" hit would still need a forward call; always
    leaving >= 1 suffix token keeps one uniform hit path.

  - Budget + LRU + pins. Entries are evicted least-recently-used when a
    new insert would exceed the byte budget; an entry is PINNED from
    lookup until the engine has dispatched the copy out of it, and
    pinned entries are never evicted (the budget must not claim back HBM
    that a copy in flight still reads).

Thread contract: all mutating calls happen on the scheduler's engine
thread (same as the engine itself). stats() may be read cross-thread —
it snapshots plain ints under the GIL, same discipline as the
scheduler's metrics dict.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any


def _digest(token_bytes: bytes) -> bytes:
    return hashlib.blake2b(token_bytes, digest_size=16).digest()


@dataclass
class PrefixEntry:
    """One cached prefix: batch-1 KV buffer + the tokens it encodes."""

    tokens: tuple[int, ...]   # the full stored prefix (aligned length)
    cache: Any                # batch-1 KVCache, capacity = build bucket
    nbytes: int
    pins: int = 0

    @property
    def length(self) -> int:
        return len(self.tokens)


@dataclass
class PrefixHit:
    """A pinned lookup result: `entry.cache[:, :, :length]` is the KV of
    `prompt[:length]`. Call release() once the copy out of the entry has
    been dispatched (idempotent — safe to call from cleanup paths)."""

    entry: PrefixEntry
    length: int               # aligned tokens usable for THIS prompt
    _store: "PrefixStore | None" = field(repr=False, default=None)
    _released: bool = False

    @property
    def group_key(self) -> tuple[int, int]:
        """Requests with equal group_key can share one seed dispatch."""
        return (id(self.entry), self.length)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._store._unpin(self.entry)


class PrefixStore:
    """LRU store of prefix KV entries under a byte budget."""

    def __init__(self, budget_bytes: int, align: int) -> None:
        if align < 1:
            raise ValueError("prefix alignment must be >= 1")
        self.budget_bytes = int(budget_bytes)
        self.align = int(align)
        # Full-prefix digest -> entry, most-recently-used LAST.
        self._entries: "OrderedDict[bytes, PrefixEntry]" = OrderedDict()
        # Boundary digest -> (entry key, boundary length). Several
        # boundaries of one entry, and boundaries of DIFFERENT entries
        # sharing a prefix, all land here; latest insert wins a contended
        # boundary (both map to identical KV content, verified at hit).
        self._index: dict[bytes, tuple[bytes, int]] = {}
        self.stats_counters = {
            "hits": 0, "misses": 0, "insertions": 0, "evictions": 0,
            "rejected": 0, "tokens_reused": 0,
        }
        self._bytes = 0
        # Count of entries with pins > 0, maintained incrementally: the
        # stats() snapshot is read from the host's stdin thread while the
        # engine thread mutates the store, so it must only copy plain
        # ints — iterating _entries cross-thread could observe a
        # mutation mid-iteration and kill the stats op.
        self._pinned = 0

    # ------------------------------------------------------------- queries

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def has(self, tokens: tuple[int, ...] | list[int]) -> bool:
        """True when an entry already covers this EXACT aligned prefix
        (used to skip redundant store dispatches)."""
        key = _digest(self._token_bytes(tokens))
        hit = self._index.get(key)
        if hit is None:
            return False
        entry = self._entries.get(hit[0])
        return (entry is not None
                and entry.tokens[:len(tokens)] == tuple(tokens))

    def lookup(self, prompt_ids: list[int]) -> PrefixHit | None:
        """Longest aligned strict prefix of `prompt_ids` with cached KV,
        pinned; None on miss. Does NOT touch the hit/miss counters: a
        request may be looked up several times before it actually admits
        (budget deferral re-resolves next block) or may fall back to a
        full prefill despite a match (no compiled continuation shape) —
        the engine counts per ADMITTED request via note_reuse/note_miss,
        so hit_rate means 'fraction of admissions that reused cached
        KV', the number the bench quotes."""
        n = len(prompt_ids)
        a = self.align
        # Strictly below n: the suffix dispatch must sample >= 1 token.
        for p in range(a * ((n - 1) // a), 0, -a):
            key = _digest(self._token_bytes(prompt_ids[:p]))
            ref = self._index.get(key)
            if ref is None:
                continue
            entry = self._entries.get(ref[0])
            if entry is None or entry.length < p:
                continue
            if entry.tokens[:p] != tuple(prompt_ids[:p]):
                continue  # digest collision — must read as a miss
            self._entries.move_to_end(ref[0])
            self._pin(entry)
            return PrefixHit(entry=entry, length=p, _store=self)
        return None

    # ------------------------------------------------------------ mutation

    def insert(self, tokens: list[int] | tuple[int, ...], cache: Any,
               nbytes: int) -> bool:
        """Adopt `cache` (batch-1 KV whose first len(tokens) positions
        encode `tokens`) under the budget; evicts LRU unpinned entries to
        make room. Returns False (and drops the buffer ref) when the
        prefix is already stored, misaligned, or cannot fit."""
        tokens = tuple(tokens)
        if not tokens or len(tokens) % self.align:
            return False
        if self.has(tokens):
            return False
        while (self._bytes + nbytes > self.budget_bytes
               and self._evict_one()):
            pass
        if self._bytes + nbytes > self.budget_bytes:
            self.stats_counters["rejected"] += 1
            return False
        entry = PrefixEntry(tokens=tokens, cache=cache, nbytes=int(nbytes))
        key = _digest(self._token_bytes(tokens))
        old = self._entries.pop(key, None)
        if old is not None:  # same digest, different tokens (collision)
            self._bytes -= old.nbytes
        self._entries[key] = entry
        self._bytes += entry.nbytes
        for p in range(self.align, entry.length + 1, self.align):
            self._index[_digest(self._token_bytes(tokens[:p]))] = (key, p)
        self.stats_counters["insertions"] += 1
        return True

    def note_reuse(self, n_requests: int, prefix_len: int) -> None:
        """Account `n_requests` ADMITTED via cached KV (one hit each)
        and the prefill tokens their dispatch skipped."""
        self.stats_counters["hits"] += n_requests
        self.stats_counters["tokens_reused"] += n_requests * prefix_len

    def note_miss(self, n_requests: int) -> None:
        """Account `n_requests` admitted WITHOUT cached KV (full
        prefill or unseeded chunked prefill)."""
        self.stats_counters["misses"] += n_requests

    def _pin(self, entry: PrefixEntry) -> None:
        entry.pins += 1
        if entry.pins == 1:
            self._pinned += 1

    def _unpin(self, entry: PrefixEntry) -> None:
        entry.pins -= 1
        if entry.pins == 0:
            self._pinned -= 1

    def _evict_one(self) -> bool:
        """Drop the least-recently-used UNPINNED entry; False when every
        entry is pinned (nothing safely evictable)."""
        for key, entry in self._entries.items():
            if entry.pins <= 0:
                del self._entries[key]
                self._bytes -= entry.nbytes
                for p in range(self.align, entry.length + 1, self.align):
                    bkey = _digest(self._token_bytes(entry.tokens[:p]))
                    if self._index.get(bkey, (None,))[0] != key:
                        continue
                    # The evicted entry may have WON this boundary from
                    # another resident entry sharing the prefix (latest
                    # insert wins) — repair the index to any survivor
                    # that still covers it, else a live prefix would
                    # silently stop hitting until its own entry churned.
                    del self._index[bkey]
                    prefix = entry.tokens[:p]
                    for okey, other in self._entries.items():
                        if (other.length >= p
                                and other.tokens[:p] == prefix):
                            self._index[bkey] = (okey, p)
                            break
                self.stats_counters["evictions"] += 1
                return True
        return False

    # --------------------------------------------------------------- misc

    @staticmethod
    def _token_bytes(tokens: list[int] | tuple[int, ...]) -> bytes:
        import numpy as np

        return np.asarray(tokens, dtype=np.int32).tobytes()

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = dict(self.stats_counters)
        out["entries"] = len(self._entries)
        out["bytes"] = self._bytes
        out["budget_bytes"] = self.budget_bytes
        out["pinned"] = self._pinned
        n = out["hits"] + out["misses"]
        out["hit_rate"] = round(out["hits"] / n, 4) if n else 0.0
        return out
