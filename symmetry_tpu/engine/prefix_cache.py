"""Radix-tree prefix cache over a paged KV block pool.

Every KV-reuse path in the engine — local shared-prefix admission,
multi-turn session history, disagg handoff adoption — lands here. The
previous design (an aligned-bucket LRU of monolithic batch-1 slabs)
could only hit on `prefix_align` boundaries and paid a slab copy for
every insert/evict/handoff. This rebuild follows the literature the
repo tracks in PAPERS.md:

  - RadixAttention (SGLang): a radix tree over token sequences makes
    EVERY shared prefix reusable — multi-turn histories of arbitrary
    length, agent trees, shared system prompts — not just the ones that
    happen to end on an alignment boundary.
  - PagedAttention (vLLM): KV lives in fixed-size blocks drawn from a
    fixed pool, so cache membership is pointer arithmetic: insert is a
    scatter of NEW blocks only, adoption of already-resident content is
    a refcount bump, and eviction frees block ids without touching HBM.

Split of responsibilities: this module is pure host-side bookkeeping
(block ids, refcounts, the tree) with no JAX dependency — the engine
owns the device-side pool array (`[L, n_blocks, block_tokens, K, D]`)
and the two compiled programs that move KV in and out of it
(`insert_from_blocks` gather-seed, `write_blocks` scatter-store). The
pool's shapes are FIXED at construction: a fixed block size, a fixed
block count, index vectors padded to each bucket's block count — zero
steady-state recompiles (symlint R3 guards the programs themselves).

Design points:

  - Match granularity is ONE BLOCK (`block_tokens`, default 16), not
    one bucket: lookup walks the tree in whole blocks and returns the
    longest block-aligned strict prefix with resident KV. Strictly
    partial only — the suffix (>= 1 token) produces the first sampled
    token, same contract as before.
  - Nodes own block lists; children are keyed by their edge's first
    block (siblings always diverge within their first block — insert
    splits edges at block boundaries to keep that invariant).
  - Eviction is leaf-LRU and frees blocks, never copies: the
    least-recently-touched leaf whose blocks are unpinned is detached
    and its block ids returned to the free list. Interior nodes become
    evictable once their children go.
  - Pins are per-block refcounts. A block's refcount is 1 while only
    the tree owns it; a `RadixHit` holds +1 on every matched block
    until `release()` (the engine releases once the seed gather out of
    the pool is dispatched). Blocks with refcount > 1 are never freed.
  - Insert is two-phase: `plan_insert` allocates block ids for the
    UNCOVERED tail (evicting leaf-LRU as needed) without touching the
    tree; the engine scatters KV into those blocks on device and then
    `commit()`s the plan (or `abort()`s on a failed dispatch, returning
    the ids). The tree therefore never references a block whose KV
    write was not dispatched.

Thread contract: all mutating calls happen on the scheduler's engine
thread. stats() may be read cross-thread — it snapshots plain ints
under the GIL, the same discipline as the scheduler's metrics dict
(no tree walks, no dict iteration over mutable containers).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any

from symmetry_tpu.utils.metrics import METRICS, MetricName


def prefix_digest(token_bytes: bytes) -> bytes:
    """Content digest used for block manifests (handoff frames) and the
    prefill tier's shipped-block ledger. A block's KV depends on EVERY
    token at or before it (causal attention), so block j's digest
    covers tokens[: (j+1) * block_tokens] — two blocks share a digest
    iff their full causal context matches."""
    return hashlib.blake2b(token_bytes, digest_size=16).digest()


def token_bytes(tokens) -> bytes:
    import numpy as np

    return np.asarray(tokens, dtype=np.int32).tobytes()


def block_digests(tokens, p: int, block_tokens: int) -> list[str]:
    """Hex digests for the p // block_tokens blocks covering
    tokens[:p], each over its full causal context (see prefix_digest).
    One running hash, copied per block — O(p) total, not O(p^2)."""
    if block_tokens < 1 or p % block_tokens:
        raise ValueError(
            f"prefix length {p} is not a multiple of block size "
            f"{block_tokens}")
    buf = token_bytes(tokens[:p])
    step = block_tokens * 4  # int32 tokens
    h = hashlib.blake2b(digest_size=16)
    out: list[str] = []
    for j in range(p // block_tokens):
        h.update(buf[j * step: (j + 1) * step])
        out.append(h.copy().digest().hex())
    return out


class BlockPool:
    """Refcounted free list over a fixed set of KV block ids.

    Block id 0 is the TRASH block: scatter dispatches are padded to each
    bucket's full block count, and every pad lane writes to the trash
    block, whose content nobody ever reads. It is never allocated.
    Ids 1..n_blocks are the allocatable pool."""

    TRASH = 0

    def __init__(self, n_blocks: int, block_tokens: int,
                 block_bytes: int) -> None:
        if n_blocks < 1:
            raise ValueError("block pool needs at least one block")
        if block_tokens < 1:
            raise ValueError("block size must be >= 1 token")
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.block_bytes = int(block_bytes)
        # refcount per id (index 0 = trash, never allocated): 0 = free,
        # 1 = tree-owned, > 1 = tree-owned and pinned by hits in flight.
        self._refs = [0] * (self.n_blocks + 1)
        self._free = list(range(self.n_blocks, 0, -1))  # pop() -> 1 first
        self._in_use = 0
        self._pinned = 0          # blocks with refs > 1
        self._high_water = 0      # peak blocks in use (bytes via property)
        self._m_in_use = METRICS.gauge(
            MetricName.PREFIX_BLOCKS_IN_USE,
            "KV blocks currently owned by the radix prefix cache")

    # ------------------------------------------------------------ queries

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self._in_use

    @property
    def pinned(self) -> int:
        return self._pinned

    @property
    def bytes_in_use(self) -> int:
        return self._in_use * self.block_bytes

    @property
    def budget_bytes(self) -> int:
        return self.n_blocks * self.block_bytes

    @property
    def hbm_high_water_bytes(self) -> int:
        """Peak pool occupancy in bytes — the per-session memory-economics
        number ROADMAP item 3 asks the bench to report. (The device pool
        array itself is allocated once at construction; this tracks how
        much of it the cache has ever actually owned.)"""
        return self._high_water * self.block_bytes

    def refcount(self, block_id: int) -> int:
        return self._refs[block_id]

    # ----------------------------------------------------------- mutation

    def alloc(self, n: int) -> list[int] | None:
        """Allocate `n` blocks at refcount 1, or None (all-or-nothing)
        when the free list is short — the caller evicts and retries."""
        if n < 0:
            raise ValueError("alloc of negative block count")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        for i in ids:
            self._refs[i] = 1
        self._in_use += n
        self._high_water = max(self._high_water, self._in_use)
        self._m_in_use.set(self._in_use)
        return ids

    def ref(self, ids) -> None:
        for i in ids:
            if self._refs[i] < 1:
                raise RuntimeError(f"ref of free block {i}")
            self._refs[i] += 1
            if self._refs[i] == 2:
                self._pinned += 1

    def unref(self, ids) -> None:
        """Drop one reference per id; a block reaching refcount 0 goes
        back to the free list."""
        for i in ids:
            r = self._refs[i] - 1
            if r < 0:
                raise RuntimeError(f"unref of free block {i}")
            self._refs[i] = r
            if r == 1:
                self._pinned -= 1
            elif r == 0:
                self._in_use -= 1
                self._free.append(i)
        self._m_in_use.set(self._in_use)


class RadixNode:
    """One tree node: an edge of whole blocks from its parent."""

    __slots__ = ("tokens", "blocks", "children", "parent", "last_used")

    def __init__(self, tokens: tuple[int, ...], blocks: list[int],
                 parent: "RadixNode | None") -> None:
        self.tokens = tokens          # edge label; len == len(blocks)*BS
        self.blocks = blocks          # pool ids, one per edge block
        self.children: dict[tuple[int, ...], RadixNode] = {}
        self.parent = parent
        self.last_used = 0


@dataclass
class RadixHit:
    """A pinned lookup result: `blocks` hold the KV of
    `prompt[:length]`, in order. Call release() once the gather out of
    the pool has been dispatched (idempotent — safe from cleanup
    paths). `group_key` partitions scheduler admissions: requests with
    equal (node, matched_len) share one seed dispatch."""

    node: RadixNode
    length: int                    # matched tokens (multiple of block size)
    blocks: tuple[int, ...]
    tokens: tuple[int, ...]        # the matched prefix itself
    _index: "RadixIndex | None" = field(repr=False, default=None)
    _released: bool = False

    @property
    def group_key(self) -> tuple[int, int]:
        return (id(self.node), self.length)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._index.pool.unref(self.blocks)


@dataclass
class InsertPlan:
    """Blocks allocated for an insert's uncovered tail; the tree learns
    about them only at commit() — after the device scatter dispatched.
    The plan PINS the matched prefix path for its lifetime: the
    eviction its own allocation may trigger (and any other eviction
    between plan and commit) must never free the blocks the new tail
    extends."""

    tokens: tuple[int, ...]        # the FULL prefix being inserted
    matched_len: int               # tokens already resident (tree-covered)
    new_ids: list[int]             # one per new block, in prefix order
    matched_blocks: tuple[int, ...] = ()   # pinned until commit/abort
    _index: "RadixIndex | None" = field(repr=False, default=None)
    _done: bool = False

    def commit(self) -> None:
        if self._done:
            raise RuntimeError("insert plan already resolved")
        self._done = True
        try:
            self._index._commit(self)
        finally:
            self._index.pool.unref(self.matched_blocks)

    def abort(self) -> None:
        if not self._done:
            self._done = True
            self._index.pool.unref(self.new_ids)
            self._index.pool.unref(self.matched_blocks)


class RadixIndex:
    """The radix tree over token sequences, indexing pool blocks."""

    def __init__(self, pool: BlockPool) -> None:
        self.pool = pool
        self.block_tokens = pool.block_tokens
        self._root = RadixNode((), [], None)
        self._tick = 0
        self._n_nodes = 0
        # Eviction candidates in LRU order (oldest first): every LEAF,
        # keyed by id(node), re-ordered on touch. Kept incrementally so
        # an insert-under-pressure pays O(evicted leaves), not a full
        # tree scan per freed leaf. A node whose last child is evicted
        # re-enters at the tail — slightly fresher than its last_used
        # tick says, a deliberate approximation (its subtree WAS in use
        # more recently than the tick).
        self._leaves: "dict[int, RadixNode]" = {}
        self.stats_counters = {
            "hits": 0, "misses": 0, "insertions": 0, "evictions": 0,
            "rejected": 0, "tokens_reused": 0, "nodes_evicted": 0,
        }
        self._m_evicted = METRICS.counter(
            MetricName.PREFIX_BLOCKS_EVICTED,
            "KV blocks freed by leaf-LRU eviction")
        self._m_hit_depth = METRICS.histogram(
            MetricName.PREFIX_HIT_DEPTH,
            "blocks matched per radix lookup hit")

    # ------------------------------------------------------------ queries

    def __len__(self) -> int:
        return self._n_nodes

    @property
    def bytes_used(self) -> int:
        return self.pool.bytes_in_use

    def _now(self) -> int:
        self._tick += 1
        return self._tick

    def _walk(self, tokens, limit_blocks: int,
              touch: bool) -> tuple[RadixNode, list[int], int]:
        """Descend from the root matching whole blocks of `tokens`, at
        most `limit_blocks`. Returns (deepest node reached, matched
        block ids in order, matched token count). `touch` refreshes
        LRU recency along the path."""
        bs = self.block_tokens
        node = self._root
        blocks: list[int] = []
        pos = 0
        now = self._now() if touch else 0
        while len(blocks) < limit_blocks:
            key = tuple(tokens[pos:pos + bs])
            child = node.children.get(key)
            if child is None:
                break
            nb = len(child.blocks)
            take = 1  # the key IS the first edge block
            while (take < nb and len(blocks) + take < limit_blocks
                   and child.tokens[take * bs:(take + 1) * bs]
                   == tuple(tokens[pos + take * bs:pos + (take + 1) * bs])):
                take += 1
            blocks.extend(child.blocks[:take])
            pos += take * bs
            if touch:
                child.last_used = now
                if not child.children:
                    # Refresh the leaf's LRU position (dicts preserve
                    # insertion order; re-inserting moves it to the
                    # tail = most recently used).
                    self._leaves.pop(id(child), None)
                    self._leaves[id(child)] = child
            node = child
            if take < nb:
                break  # diverged (or hit the limit) inside this edge
        return node, blocks, pos

    def lookup(self, prompt_ids) -> RadixHit | None:
        """Longest block-aligned strict prefix of `prompt_ids` with
        resident KV, pinned; None on miss. Does NOT touch the hit/miss
        counters — the engine counts per ADMITTED request via
        note_reuse/note_miss (a request may be looked up several times
        before it actually admits), so hit_rate means 'fraction of
        admissions that reused cached KV'."""
        n = len(prompt_ids)
        limit = (n - 1) // self.block_tokens  # suffix must keep >= 1 token
        if limit <= 0:
            return None
        node, blocks, pos = self._walk(prompt_ids, limit, touch=True)
        if not blocks:
            return None
        self.pool.ref(blocks)
        self._m_hit_depth.observe(len(blocks))
        return RadixHit(node=node, length=pos, blocks=tuple(blocks),
                        tokens=tuple(prompt_ids[:pos]), _index=self)

    def match_len(self, tokens) -> int:
        """Resident coverage of `tokens` in whole blocks (token count;
        NOT capped below len(tokens) — used by insert planning and
        adoption, where full coverage means nothing to do)."""
        _, _, pos = self._walk(tokens, len(tokens) // self.block_tokens,
                               touch=False)
        return pos

    def covers(self, tokens) -> bool:
        """True when every whole block of `tokens` is already resident
        (used to skip redundant store dispatches)."""
        p = (len(tokens) // self.block_tokens) * self.block_tokens
        return p == 0 or self.match_len(tokens) >= p

    # ----------------------------------------------------------- mutation

    def plan_insert(self, tokens) -> InsertPlan | None:
        """Allocate blocks for the uncovered tail of `tokens` (whose
        length must be a whole number of blocks), evicting leaf-LRU
        until they fit. The matched prefix path is PINNED (refcounted)
        for the plan's lifetime — the eviction this very allocation
        triggers must never free the blocks the tail extends. None when
        `tokens` is fully resident, empty, or cannot fit even after
        eviction (counted as rejected)."""
        bs = self.block_tokens
        p = len(tokens)
        if p == 0 or p % bs:
            return None
        _node, matched, m = self._walk(tokens, p // bs, touch=True)
        need = (p - m) // bs
        if need == 0:
            return None
        self.pool.ref(matched)
        ids = None
        try:
            ids = self.pool.alloc(need)
            while ids is None and self._evict_one():
                ids = self.pool.alloc(need)
            if ids is not None:
                return InsertPlan(tokens=tuple(tokens), matched_len=m,
                                  new_ids=ids,
                                  matched_blocks=tuple(matched),
                                  _index=self)
        except Exception:
            # An eviction failure (or anything else between alloc and
            # the plan handoff) must not leak the matched-prefix pin or
            # the freshly allocated blocks.
            if ids is not None:
                self.pool.unref(ids)
            self.pool.unref(matched)
            raise
        self.pool.unref(matched)
        self.stats_counters["rejected"] += 1
        return None

    def _commit(self, plan: InsertPlan) -> None:
        """Attach the plan's blocks to the tree, splitting the edge at
        the divergence boundary when needed so siblings keep diverging
        within their first block."""
        bs = self.block_tokens
        tokens = plan.tokens
        # Re-walk: the tree may have changed between plan and commit
        # only via THIS thread (engine-thread contract), and a commit
        # always directly follows its plan — but re-walking keeps the
        # structure correct even if that ever changes, at negligible
        # cost. The matched coverage is the plan's by construction.
        node, _, pos = self._walk(tokens, plan.matched_len // bs,
                                  touch=False)
        if pos != plan.matched_len:
            # The resident prefix changed between plan and commit —
            # engine-thread contract broken. Fail loudly, free the ids.
            self.pool.unref(plan.new_ids)
            raise RuntimeError(
                f"radix commit raced an eviction/insert: planned match "
                f"{plan.matched_len}, found {pos}")
        # `node` is the deepest node on the path; if the match ended
        # INSIDE node's edge, split it at the boundary.
        depth_into = pos - self._depth_of_parent(node)
        if node is not self._root and depth_into < len(node.tokens):
            node = self._split(node, depth_into)
        child = RadixNode(tokens=tuple(tokens[pos:]),
                          blocks=list(plan.new_ids), parent=node)
        child.last_used = self._now()
        node.children[tuple(tokens[pos:pos + bs])] = child
        self._leaves.pop(id(node), None)   # gained a child: not a leaf
        self._leaves[id(child)] = child
        self._n_nodes += 1
        self.stats_counters["insertions"] += 1

    def _depth_of_parent(self, node: RadixNode) -> int:
        """Token depth at which `node`'s edge starts."""
        d = 0
        cur = node.parent
        while cur is not None:
            d += len(cur.tokens)
            cur = cur.parent
        return d

    def _split(self, node: RadixNode, at: int) -> RadixNode:
        """Split `node`'s edge at token offset `at` (a block boundary
        inside the edge); returns the new upper node. Block ownership
        moves with the tokens; refcounts are untouched (same owners)."""
        bs = self.block_tokens
        assert 0 < at < len(node.tokens) and at % bs == 0
        upper = RadixNode(tokens=node.tokens[:at],
                          blocks=node.blocks[:at // bs],
                          parent=node.parent)
        upper.last_used = node.last_used
        parent_key = node.tokens[:bs]
        node.parent.children[parent_key] = upper
        node.tokens = node.tokens[at:]
        node.blocks = node.blocks[at // bs:]
        node.parent = upper
        upper.children[node.tokens[:bs]] = node
        self._n_nodes += 1
        return upper

    def _evict_one(self) -> bool:
        """Detach the least-recently-used LEAF whose blocks are all
        unpinned and free its blocks; False when nothing is safely
        evictable. Walks the incrementally-maintained LRU leaf registry,
        skipping pinned leaves in place — O(pinned prefix) per evicted
        leaf, never a tree scan, never on the lookup fast path."""
        victim: RadixNode | None = None
        for node in self._leaves.values():
            if all(self.pool.refcount(b) == 1 for b in node.blocks):
                victim = node
                break  # oldest unpinned leaf
        if victim is None:
            return False
        del self._leaves[id(victim)]
        del victim.parent.children[victim.tokens[:self.block_tokens]]
        parent = victim.parent
        if parent is not self._root and not parent.children:
            self._leaves[id(parent)] = parent  # exposed: evictable next
        self.pool.unref(victim.blocks)
        self._n_nodes -= 1
        self.stats_counters["evictions"] += len(victim.blocks)
        self.stats_counters["nodes_evicted"] += 1
        self._m_evicted.inc(len(victim.blocks))
        return True

    # --------------------------------------------------------- accounting

    def note_reuse(self, n_requests: int, prefix_len: int) -> None:
        """Account `n_requests` ADMITTED via cached KV (one hit each)
        and the prefill tokens their dispatch skipped."""
        self.stats_counters["hits"] += n_requests
        self.stats_counters["tokens_reused"] += n_requests * prefix_len

    def note_miss(self, n_requests: int) -> None:
        self.stats_counters["misses"] += n_requests

    def summary(self, max_digests: int = 64) -> dict[str, Any] | None:
        """Compact cache summary for pool gossip: up to `max_digests`
        block digests along the HOTTEST root→leaf paths (most recently
        used first — the prefixes a session-affine router should chase)
        plus a depth histogram of those paths, in blocks. Rides the
        stats probe as a heartbeat payload field; the PoolRouter on the
        provider side intersects a request's own digests against it to
        predict hit depth.

        Digests are the same causal blake2b-16 hexes as the handoff
        manifests (`block_digests`), so router-side digests computed
        from the routing tokenizer's prompt ids match exactly.

        Unlike every mutating call, this may run OFF the engine thread
        (the host's serve loop answers STATS while the engine thread
        inserts/evicts). Reads are GIL-atomic snapshots but a racing
        split/evict can garble one path — a garbled digest is only a
        wrong routing hint, so the whole walk is exception-guarded:
        degrade (None → load-only placement), never wedge."""
        if max_digests <= 0:
            return None
        try:
            digests: dict[str, None] = {}  # ordered de-dup
            depths: dict[int, int] = {}
            for leaf in reversed(list(self._leaves.values())):
                if len(digests) >= max_digests:
                    break
                # Root-path tokens via the parent chain (leaf-upward,
                # then reversed into prefix order).
                parts: list[tuple[int, ...]] = []
                node: RadixNode | None = leaf
                while node is not None and node.parent is not None:
                    parts.append(node.tokens)
                    node = node.parent
                tokens: list[int] = []
                for part in reversed(parts):
                    tokens.extend(part)
                p = (len(tokens) // self.block_tokens) * self.block_tokens
                if p == 0:
                    continue
                depth = p // self.block_tokens
                depths[depth] = depths.get(depth, 0) + 1
                for d in block_digests(tokens, p, self.block_tokens):
                    digests.setdefault(d, None)
            if not digests:
                return None
            return {"block_tokens": self.block_tokens,
                    "digests": list(digests)[:max_digests],
                    "depths": {str(k): v
                               for k, v in sorted(depths.items())}}
        except Exception:
            return None

    def stats(self) -> dict[str, Any]:
        out: dict[str, Any] = dict(self.stats_counters)
        pool = self.pool
        out["nodes"] = self._n_nodes
        out["block_tokens"] = pool.block_tokens
        out["blocks_total"] = pool.n_blocks
        out["blocks_in_use"] = pool.in_use
        out["blocks_free"] = pool.free_count
        out["pinned"] = pool.pinned
        out["bytes"] = pool.bytes_in_use
        out["budget_bytes"] = pool.budget_bytes
        out["hbm_high_water_bytes"] = pool.hbm_high_water_bytes
        n = out["hits"] + out["misses"]
        out["hit_rate"] = round(out["hits"] / n, 4) if n else 0.0
        return out
