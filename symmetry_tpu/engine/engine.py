"""The serving engine: jitted prefill / insert / decode over a slot batch.

Shape discipline (SURVEY §7 hard-part 1 — continuous batching under jit
without recompile storms):

  - PREFILL runs at batch 1, prompt padded to one of a few fixed buckets
    (tpu.prefill_buckets) — one compiled program per bucket, ever.
  - INSERT copies the prefilled KV prefix into slot `i` of the shared decode
    cache with dynamic_update_slice — shapes static, slot index dynamic.
  - DECODE advances ALL slots one token per step at a fixed [B, 1] shape;
    per-slot raggedness lives in position/length arrays, not shapes.

All three are donated-state jits: the decode cache (the big HBM tenant) is
updated in place, never copied. Sampling controls are per-slot device arrays
so one compiled step serves mixed greedy/sampled requests.

The engine is synchronous and single-threaded by design — the asyncio bridge
lives in the scheduler (scheduler.py), mirroring how the reference keeps all
concurrency in one event loop (SURVEY §5.2).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from symmetry_tpu.models.llama import (
    KVCache,
    ModelConfig,
    cache_logical_axes,
    forward_hidden,
    init_cache,
    init_params,
    logits_from_hidden,
    preset,
)


from symmetry_tpu.ops.sampling import sample_tokens, verify_tokens
from symmetry_tpu.parallel.mesh import MeshSpec, build_mesh
from symmetry_tpu.parallel.sharding import shardings_for
from symmetry_tpu.engine.prefix_cache import BlockPool, RadixHit, RadixIndex
from symmetry_tpu.engine.spec import SpecConfig
from symmetry_tpu.engine.tokenizer import Tokenizer, get_tokenizer


def _stage_rules(mesh):
    """PIPELINE_RULES when the mesh has an active stage axis, else None —
    the ONE place pipeline-mode detection lives (constructor, jit builder,
    and from_tpu_config all route through it)."""
    if mesh is not None and dict(mesh.shape).get("stage", 1) > 1:
        from symmetry_tpu.parallel.pipeline import PIPELINE_RULES

        return PIPELINE_RULES
    return None


class EngineError(RuntimeError):
    pass


class DecodeState(NamedTuple):
    """Everything the decode step needs, all static-shape device arrays."""

    cache: KVCache            # [L, B, T, K, D] x2 + lengths [B]
    last_token: jnp.ndarray   # [B] int32 — token to feed next step
    temperature: jnp.ndarray  # [B] float32
    top_p: jnp.ndarray        # [B] float32
    top_k: jnp.ndarray        # [B] int32
    rng: jax.Array            # [B] PRNG keys — one stream PER SLOT, seeded
                              # at insert: a seeded request reproduces its
                              # whole completion and no slot's sampling is
                              # perturbed by other traffic


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int | None = None
    # Stream resumption: how many tokens of this request's completion
    # were already sampled (and streamed) before this admission. For a
    # SEEDED request the engine fast-forwards the per-slot PRNG chain by
    # this many draws, so the resumed continuation samples exactly the
    # tokens the uninterrupted run would have — seeded resumes are
    # token-identical, not just greedy ones. Ignored without a seed
    # (unseeded lanes use per-request process entropy, which a new host
    # cannot reproduce anyway; greedy never consults the RNG).
    # Caveat: the one-draw-per-token chain holds for the plain decode
    # path only — a speculative verify dispatch consumes ONE split while
    # emitting several tokens, so seeded SAMPLED identity under
    # tpu.speculative is out of scope (it already isn't reproducible
    # across spec on/off: rejection sampling draws differently); greedy
    # resumes stay exact everywhere because greedy never reads the lane.
    rng_skip: int = 0

    @classmethod
    def from_request(cls, req: Any) -> "SamplingParams":
        return cls(
            temperature=req.temperature if req.temperature is not None else 0.0,
            top_p=req.top_p if req.top_p is not None else 1.0,
            top_k=getattr(req, "top_k", None) or 0,
            seed=req.seed,
        )


@dataclass
class ChunkedPrefill:
    """An in-progress chunked prefill: one prompt's KV prefix being built
    chunk-by-chunk so long-prompt admission never stalls active decode
    streams for more than ~one chunk (round-2 verdict: a 2048-bucket
    prefill froze every stream for ~0.6 s).

    With `start_pos` > 0 the cache was SEEDED from a prefix-cache entry
    (the first start_pos positions already hold that prefix's KV) and
    `ids` carries only the uncached suffix — the chunk loop then covers
    suffix tokens only."""

    slot: int
    ids: np.ndarray           # [1, n_chunks * C] padded suffix tokens
    true_len: int             # FULL prompt length (prefix + suffix)
    n_chunks: int
    cache: Any                # batch-1 prefix KVCache (bucket capacity)
    temp: jnp.ndarray         # [1]
    top_p: jnp.ndarray        # [1]
    top_k: jnp.ndarray        # [1]
    prefill_key: jax.Array    # [1] PRNG for the first-token sample
    decode_key: jax.Array     # [1] PRNG stream carried into decode
    done_chunks: int = 0
    start_pos: int = 0        # tokens already in the cache at start
    full_ids: tuple[int, ...] = ()  # the whole prompt (prefix-store key)

    @property
    def remaining_chunks(self) -> int:
        return self.n_chunks - self.done_chunks

    @property
    def suffix_len(self) -> int:
        return self.true_len - self.start_pos


class InferenceEngine:
    """Owns params + decode state; exposes prefill/insert/decode primitives.

    Thread-safety: NOT thread-safe; exactly one thread (the scheduler's
    engine thread) may call the mutating methods.
    """

    def __init__(
        self,
        config: ModelConfig,
        params: Any,
        tokenizer: Tokenizer,
        *,
        mesh=None,
        max_slots: int = 8,
        max_seq_len: int = 2048,
        prefill_buckets: tuple[int, ...] = (128, 512, 2048),
        cache_dtype=jnp.bfloat16,
        decode_block: int = 1,
        kv_quant: bool = False,
        pipeline_microbatches: int = 1,
        prefill_chunk: int | None = 256,
        prefill_token_budget: int | None = None,
        prefix_cache_bytes: int = 0,
        prefix_block_tokens: int = 16,
        prefix_gossip_blocks: int = 64,
        prefix_gossip_s: float = 2.0,
        speculative: SpecConfig | None = None,
        fused_dequant: bool = False,
        role: str = "unified",
        profile_sample: int = 0,
    ) -> None:
        self.config = config
        self.params = params
        self.tokenizer = tokenizer
        self.mesh = mesh
        # Disaggregated prefill/decode (engine/disagg/): "unified" is
        # today's engine — prefill AND decode on this chip. "prefill"
        # builds prompt KV and hands it off (never decodes; warmup skips
        # every decode-side compile); "decode" adopts handed-off KV
        # through the prefix store and generates. Role selection changes
        # NO compiled program — it only gates which of the existing
        # programs warmup builds and which scheduler paths run.
        if role not in ("unified", "prefill", "decode"):
            raise EngineError(
                f"unknown engine role {role!r}; expected unified, "
                f"prefill, or decode (disagg is a backend-level role — "
                f"the broker assigns prefill/decode to its two hosts)")
        if role != "unified" and mesh is not None:
            # Handoff frames are host-side numpy snapshots; a sharded
            # cache on a multi-process mesh is not host-addressable.
            # Loud, not silently wrong — same contract as fused_dequant.
            raise EngineError(
                f"tpu.role {role!r} supports single-device engines only "
                f"(KV handoff snapshots the cache host-side); drop the "
                f"role or the mesh")
        self.role = role
        # W8A16 fused-dequant routing (tpu.fused_dequant): pack the int8
        # weight leaves into the Pallas kernel's tile layout ONCE, here —
        # the layout is the routing (qmatmul dispatches on the leaf
        # type), so every trunk program built below (prefill, chunk,
        # decode, verify) traces fused with no extra knob plumbing, and
        # knob-off leaves every compiled program byte-identical to a
        # build without the feature. On a mesh the pack happens AFTER
        # the sharding decision: pack_params resolves each leaf's
        # contraction/output mesh axes from the same logical-axis tree
        # the dense placement used, picks tile blocks against the
        # per-shard dims, and qmatmul routes the leaf through the
        # shard_map'd per-shard kernel. Leaves that can't shard-pack
        # degrade to the mixed dot — loudly (log + counter), never
        # silently.
        self.fused_dequant = bool(fused_dequant)
        if self.fused_dequant:
            from symmetry_tpu.models.llama import pack_params
            from symmetry_tpu.ops.quant import (
                PackedQuantizedTensor, QuantizedTensor)
            from symmetry_tpu.utils.logging import logger
            from symmetry_tpu.utils.metrics import METRICS, MetricName

            def is_qt(leaf):
                return isinstance(leaf, QuantizedTensor)

            if not any(is_qt(leaf) for leaf in
                       jax.tree.leaves(params, is_leaf=is_qt)):
                raise EngineError(
                    "tpu.fused_dequant found no packable int8 weights — "
                    "it requires tpu.quantization: int8 (the knob would "
                    "otherwise be silently inert)")
            fallback = METRICS.counter(
                MetricName.QMM_FALLBACK,
                "int8 leaves kept on the mixed dot at load",
                labels=("reason",))
            if _stage_rules(mesh) is not None:
                # Pipeline stages run the trunk inside their own
                # shard_map collectives; the fused kernel's per-shard
                # dispatch cannot nest there. Degrade the whole tree —
                # the engine serves unfused, and says so.
                logger.warning(
                    "tpu.fused_dequant: pipeline (stage axis > 1) keeps "
                    "every int8 leaf on the mixed dot (reason: "
                    "stage_axis)")
                fallback.inc(reason="stage_axis")
            else:
                degrades: list[tuple[str, str]] = []
                self.params = params = pack_params(
                    params, config=config, mesh=mesh, report=degrades)
                for path, reason in degrades:
                    logger.warning(
                        f"tpu.fused_dequant: {path} stays on the mixed "
                        f"dot (reason: {reason})")
                    fallback.inc(reason=reason)

                def is_packed(leaf):
                    return isinstance(leaf, PackedQuantizedTensor)

                if not any(is_packed(leaf) for leaf in
                           jax.tree.leaves(params, is_leaf=is_packed)):
                    logger.warning(
                        "tpu.fused_dequant: no int8 leaf packed on this "
                        "mesh/backend — the engine runs entirely on the "
                        "mixed dot (see the degrade reasons above)")
        # Pipeline-parallel serving (parallel/pipeline.py): a stage axis of
        # size > 1 routes prefill AND decode through the staged microbatch
        # schedule; params/cache must be stage-sharded (PIPELINE_RULES).
        self._rules = _stage_rules(mesh)
        self.pipeline = self._rules is not None
        if self.pipeline and max_slots % pipeline_microbatches:
            raise EngineError(
                f"max_slots {max_slots} must divide into "
                f"{pipeline_microbatches} pipeline microbatches")
        if pipeline_microbatches > 1 and not self.pipeline:
            raise EngineError(
                "pipeline_microbatches > 1 requires a mesh with a stage "
                "axis > 1 — the setting would otherwise be silently inert")
        self.pipeline_microbatches = pipeline_microbatches
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.prefill_buckets = tuple(sorted(b for b in prefill_buckets
                                            if b <= max_seq_len))
        if not self.prefill_buckets:
            raise EngineError("no prefill bucket fits within max_seq_len")
        self.cache_dtype = cache_dtype
        self.kv_quant = kv_quant
        if decode_block < 1:
            raise EngineError("decode_block must be >= 1")
        # Prompts that leave less than decode_block headroom finish right
        # after their first token (scheduler admission check), so buckets up
        # to max_seq_len are allowed — they just can't decode far.
        self.decode_block = decode_block
        if prefill_chunk is not None and prefill_chunk < 1:
            raise EngineError("prefill_chunk must be >= 1 (or None)")
        self.prefill_chunk = prefill_chunk
        self.prefill_token_budget = (prefill_token_budget
                                     if prefill_token_budget is not None
                                     else self.PREFILL_TOKEN_BUDGET)
        if self.prefill_token_budget < 1:
            raise EngineError("prefill_token_budget must be >= 1")
        # symprof (utils/devprof.py, tpu.profile_sample): sampling
        # completion probes around every dispatch kind below — per-kind
        # DEVICE durations + the dispatch-gap series. Off (0) = one
        # branch per dispatch: every hook is guarded by `dp.enabled`.
        from symmetry_tpu.utils.devprof import DeviceProfiler

        self.devprof = DeviceProfiler(profile_sample)

        c = config

        if mesh is not None:
            rules = self._rules
            cax = cache_logical_axes(quantized=kv_quant)
            rep = jax.NamedSharding(mesh, jax.sharding.PartitionSpec())
            sc = (shardings_for(cax.k_scale, mesh, rules)
                  if kv_quant else None)
            self._cache_shardings = KVCache(
                k=shardings_for(cax.k, mesh, rules),
                v=shardings_for(cax.v, mesh, rules),
                # lengths stays REPLICATED (O(slots) int32): the host reads
                # individual slots, and on a multi-process data axis a
                # batch-sharded slot may live on another host.
                lengths=rep,
                k_scale=sc, v_scale=sc,
            )
            self._state_shardings = DecodeState(
                cache=self._cache_shardings, last_token=rep, temperature=rep,
                top_p=rep, top_k=rep, rng=rep)
        else:
            self._cache_shardings = None
            self._state_shardings = None

        def _init_state() -> DecodeState:
            return DecodeState(
                cache=init_cache(c, max_slots, max_seq_len, cache_dtype,
                                 quantized=kv_quant),
                last_token=jnp.zeros((max_slots,), jnp.int32),
                temperature=jnp.zeros((max_slots,), jnp.float32),
                top_p=jnp.ones((max_slots,), jnp.float32),
                top_k=jnp.zeros((max_slots,), jnp.int32),
                rng=jax.random.split(jax.random.key(0), max_slots),
            )

        if self._state_shardings is not None:
            # Initial placement must match the jits' out_shardings exactly
            # (donated-buffer aliasing on the first insert), and must work
            # when the mesh spans processes — jit-with-out_shardings creates
            # the global arrays in place; device_put of host values cannot
            # address other hosts' devices.
            self.state = jax.jit(_init_state,
                                 out_shardings=self._state_shardings)()
        else:
            self.state = _init_state()

        self._base_key = jax.random.key(
            int.from_bytes(os.urandom(4), "little"))
        self._requests_served = 0
        # (batch, bucket) -> persistent donated prefix buffer; see
        # _prefill_scratch_for.
        self._prefill_scratch: dict[tuple[int, int], Any] = {}

        # Radix-tree prefix cache over a paged KV block pool
        # (prefix_cache.py). `prefix_align` is the compiled SUFFIX width
        # of the one-dispatch hit path (min(prefill_chunk, smallest
        # bucket), unchanged from the aligned-store days); MATCHING now
        # happens at `prefix_block` granularity — any whole-block shared
        # prefix hits, bucket boundaries no longer matter. Off by
        # default (budget 0): the default serving path then performs
        # literally zero extra work — no lookups, no pool allocation,
        # no extra warmup compiles.
        self.prefix_align = (min(self.prefill_chunk, self.prefill_buckets[0])
                             if self.prefill_chunk is not None else None)
        self.prefix_block = int(prefix_block_tokens)
        if self.prefix_block < 1:
            raise EngineError("prefix_block_tokens must be >= 1")
        self.block_pool: BlockPool | None = None
        self.prefix_index: RadixIndex | None = None
        self._pool_kv = None
        # Pool-gossip rider sizing/cadence (tpu.prefix_gossip_blocks /
        # tpu.prefix_gossip_s): how many hot-path block digests the
        # cache summary carries on each stats probe, and the minimum
        # recompute interval (the summary walk is O(digests), but the
        # stats probe fires per heartbeat per member — cache it).
        self.prefix_gossip_blocks = int(prefix_gossip_blocks)
        self.prefix_gossip_s = float(prefix_gossip_s)
        self._gossip_cache: tuple[float, dict | None] | None = None
        if prefix_cache_bytes > 0 and self.prefix_align:
            # Only a BUILT pool constrains the bucket grid (the gather/
            # scatter programs index buckets in whole blocks); with the
            # cache off, prefix_block is only the handoff slicing unit
            # and any bucket set that worked before keeps working.
            for b in self.prefill_buckets:
                if b % self.prefix_block:
                    raise EngineError(
                        f"prefix_block_tokens {self.prefix_block} must "
                        f"divide every prefill bucket (bucket {b} does "
                        f"not) — the block gather/scatter programs "
                        f"index buckets in whole blocks")
            block_bytes = self.prefix_block * self.kv_bytes_per_token()
            n_blocks = int(prefix_cache_bytes) // block_bytes
            if self.role == "decode":
                # Geometry-derived floor, not a fixed MB knob: adoption
                # of a largest-bucket prompt must never be rejected by a
                # default budget too small for the model at hand — the
                # prefill tier's work would ship across the pipe and be
                # thrown away, strictly worse than unified mode. Two
                # largest prefixes' worth keeps one pinned mid-copy
                # while the next adopts.
                n_blocks = max(n_blocks, 2 * (self.prefill_buckets[-1]
                                              // self.prefix_block))
            # The pool must at least hold one smallest-bucket prefix or
            # every insert is a guaranteed rejection.
            n_blocks = max(n_blocks,
                           self.prefill_buckets[0] // self.prefix_block)
            self.block_pool = BlockPool(n_blocks, self.prefix_block,
                                        block_bytes)
            self.prefix_index = RadixIndex(self.block_pool)
        if self.role == "decode" and self.prefix_index is None:
            # Adoption lands handed-off KV through the radix index;
            # without it every migrated request would silently
            # re-prefill from scratch — the exact work the prefill tier
            # already did.
            raise EngineError(
                "role: decode requires the prefix cache "
                "(tpu.prefix_cache_mb > 0 and a prefill_chunk) — "
                "handoff frames are adopted through it")
        if self.role == "prefill" and not self.prefix_align:
            raise EngineError(
                "role: prefill requires tpu.prefill_chunk — the decode "
                "tier's suffix dispatch needs a compiled shape")

        # Speculative decoding (engine/spec/): None keeps the serving path
        # byte-identical — no verify jit is ever built or compiled, the
        # scheduler never drafts, warmup's compile set is unchanged.
        self.spec = speculative
        if self.spec is not None and 1 + self.spec.k_draft > max_seq_len:
            raise EngineError(
                f"speculative k_draft {self.spec.k_draft} does not fit "
                f"max_seq_len {max_seq_len}")

        self._build_jits()

        if self.block_pool is not None:
            # The device half of the pool: one KVCache whose "batch" axis
            # is block ids and whose position capacity is one block —
            # [L, n_blocks + 1, block_tokens, K, D] (+1 for the trash
            # block scatter pads write to). Allocated ONCE here; every
            # insert/evict/adopt thereafter is pointer bookkeeping plus
            # at most one fixed-shape gather or scatter.
            self._pool_kv = self._new_pool_kv()

    def _new_pool_kv(self):
        c = self.config
        slots = self.block_pool.n_blocks + 1  # id 0 is the trash block

        def make():
            return init_cache(c, slots, self.prefix_block,
                              self.cache_dtype, quantized=self.kv_quant)

        if self.mesh is not None:
            return jax.jit(make, out_shardings=self._prefix_shard)()
        return jax.jit(make)()

    # ------------------------------------------------------------------
    # Jitted primitives

    def _build_jits(self) -> None:
        cfg = self.config

        def trunk(params, tokens, cache, seq_lens=None, prefill_flash=False):
            """forward_hidden, routed through the pipeline schedule when a
            stage axis is active (params/cache are stage-sharded then)."""
            if self.pipeline:
                from symmetry_tpu.parallel.pipeline import (
                    pipeline_forward_hidden)

                n_micro = (self.pipeline_microbatches
                           if tokens.shape[0] == self.max_slots else 1)
                return pipeline_forward_hidden(
                    params, cfg, tokens, cache, self.mesh,
                    seq_lens=seq_lens, n_microbatches=n_micro,
                    prefill_flash=prefill_flash)
            return forward_hidden(params, cfg, tokens, cache,
                                  seq_lens=seq_lens,
                                  prefill_flash=prefill_flash,
                                  # The fused Pallas KV append has no
                                  # GSPMD partitioning rule; sharded
                                  # caches keep the XLA scatter path.
                                  kv_append_ok=self.mesh is None)

        def prefill(params, tokens, true_len, temp, top_p, top_k, rng,
                    scratch):
            """tokens [N, Sb] padded; returns (first tokens [N], prefix KV).

            N > 1 is COALESCED prefill (scheduler batches concurrent
            arrivals into one dispatch — each dispatch costs a full
            host↔device round-trip, so admission bursts would otherwise
            serialize into p99 TTFT).

            `scratch` is the PERSISTENT prefix buffer for this (batch,
            bucket) shape, donated in and returned as the prefix: a fresh
            init_cache per dispatch allocated+freed the largest transient
            in serving (hundreds of MB per dispatch), and that churn on a
            ~95%-full HBM intermittently wedged mid-traffic prefills in a
            multi-minute allocation retry (round-4 stagger run). The
            prefill-from-empty trunk overwrites EVERY position/scale of
            the buffer (flash attention never reads it), so dirty reuse
            is sound — EXCEPT lengths, which position the writes and
            carry the previous use's values: reset to the empty-cache
            contract first."""
            cache = scratch._replace(
                lengths=jnp.zeros_like(scratch.lengths))
            h, cache = trunk(params, tokens, cache,
                             seq_lens=true_len, prefill_flash=True)
            # Project ONLY the last valid position through the LM head —
            # head cost is per-position × vocab, and padded positions are
            # garbage anyway.
            h_last = jnp.take_along_axis(
                h, (true_len - 1)[:, None, None].astype(jnp.int32),
                axis=1)  # [N, 1, E]
            last = logits_from_hidden(params, cfg, h_last)[:, 0]  # [N, V]
            toks = sample_tokens(last, rng, temp, top_p, top_k)  # [N] keys
            return toks, cache

        def insert(state: DecodeState, prefix: KVCache, row, slot, true_len,
                   first_token, temp, top_p, top_k, rng) -> DecodeState:
            """Copy row `row` of a batch-N prefilled prefix into decode
            slot `slot` (scalars arrive as [N] arrays, indexed by row)."""

            def place(big, small_batch):
                # big [L,B,T,...] <- small_batch[:, row] at [:, slot, 0]
                # (KV payloads are rank 5, scale planes rank 4)
                sizes = (small_batch.shape[0], 1) + small_batch.shape[2:]
                src = (0, row) + (0,) * (small_batch.ndim - 2)
                small = jax.lax.dynamic_slice(small_batch, src, sizes)
                start = (0, slot, 0) + (0,) * (big.ndim - 3)
                return jax.lax.dynamic_update_slice(
                    big, small.astype(big.dtype), start)

            cache = state.cache._replace(
                k=place(state.cache.k, prefix.k),
                v=place(state.cache.v, prefix.v),
                # The first sampled token's KV is not here yet: the next
                # decode step writes it at position true_len.
                lengths=state.cache.lengths.at[slot].set(true_len[row]),
                **({"k_scale": place(state.cache.k_scale, prefix.k_scale),
                    "v_scale": place(state.cache.v_scale, prefix.v_scale)}
                   if self.kv_quant else {}),
            )
            return DecodeState(
                cache=cache,
                last_token=state.last_token.at[slot].set(first_token[row]),
                temperature=state.temperature.at[slot].set(temp[row]),
                top_p=state.top_p.at[slot].set(top_p[row]),
                top_k=state.top_k.at[slot].set(top_k[row]),
                # The request's own PRNG stream continues into decode: a
                # seeded request reproduces its whole completion.
                rng=state.rng.at[slot].set(rng[row]),
            )

        def insert_all(state: DecodeState, prefix: KVCache, slots,
                       true_len, first_token, temp, top_p, top_k,
                       rng) -> DecodeState:
            """Install EVERY row of a coalesced prefill in ONE dispatch —
            per-row insert calls each cost a host↔device round-trip
            (~100 ms over a tunnel), which dominated burst-admission TTFT.
            Pad rows carry the last real request's slot: re-inserting
            identical data to the same slot is idempotent."""

            def body(i, st):
                return insert(st, prefix, i, slots[i], true_len,
                              first_token, temp, top_p, top_k, rng)

            return jax.lax.fori_loop(0, slots.shape[0], body, state)

        def insert_from_blocks(scratch: KVCache, pool: KVCache, ids, p):
            """Seed a donated (batch, bucket) working prefix buffer from
            pool blocks: `ids` [bucket // prefix_block] names the block
            covering each bucket position span (pad lanes carry the
            trash block — their gathered garbage lands at positions >= p
            which the suffix continuation never attends), `p` is the
            matched prefix length every row's lengths become. ONE
            compiled program per (batch, bucket) — the ids vector's
            shape is fixed by the bucket, the block ids are data. The
            suffix continuation (chunk_step/chunk_final) then runs from
            these lengths exactly like a chunked prefill that had
            already built p tokens."""
            B = scratch.k.shape[1]

            def gather(parr, big):
                sel = jnp.take(parr, ids, axis=1)      # [L, nb, PB, K, D]
                seq = sel.reshape(
                    (sel.shape[0], 1, sel.shape[1] * sel.shape[2])
                    + sel.shape[3:])
                return jnp.broadcast_to(
                    seq, (seq.shape[0], B) + seq.shape[2:]).astype(big.dtype)

            def gather_scale(parr, big):
                sel = jnp.take(parr, ids, axis=1)      # [L, nb, K, PB]
                sel = jnp.moveaxis(sel, 1, 2)          # [L, K, nb, PB]
                seq = sel.reshape(sel.shape[0], 1, sel.shape[1],
                                  sel.shape[2] * sel.shape[3])
                return jnp.broadcast_to(
                    seq, (seq.shape[0], B) + seq.shape[2:]).astype(big.dtype)

            return scratch._replace(
                k=gather(pool.k, scratch.k),
                v=gather(pool.v, scratch.v),
                lengths=jnp.full_like(scratch.lengths, p),
                **({"k_scale": gather_scale(pool.k_scale, scratch.k_scale),
                    "v_scale": gather_scale(pool.v_scale, scratch.v_scale)}
                   if self.kv_quant else {}),
            )

        def write_blocks(pool: KVCache, row: KVCache, ids):
            """Scatter a batch-1 row buffer (capacity = one bucket) into
            pool blocks: bucket span j lands in pool block ids[j]. Spans
            that should NOT be stored (already-resident prefix blocks,
            positions past the prefix) point their lane at the trash
            block — the scatter stays one fixed shape per bucket and
            unwanted writes go where nobody reads. The pool is donated:
            membership changes in place, never by copy."""
            PB = self.prefix_block

            def put(parr, rarr):
                src = rarr[:, 0].reshape(
                    (rarr.shape[0], ids.shape[0], PB) + rarr.shape[3:])
                return parr.at[:, ids].set(src.astype(parr.dtype))

            def put_scale(parr, rarr):
                src = rarr[:, 0].reshape(rarr.shape[0], rarr.shape[2],
                                         ids.shape[0], PB)
                src = jnp.moveaxis(src, 2, 1)          # [L, nb, K, PB]
                return parr.at[:, ids].set(src.astype(parr.dtype))

            return pool._replace(
                k=put(pool.k, row.k),
                v=put(pool.v, row.v),
                **({"k_scale": put_scale(pool.k_scale, row.k_scale),
                    "v_scale": put_scale(pool.v_scale, row.v_scale)}
                   if self.kv_quant else {}),
            )

        def extract_prefix_row(prefix: KVCache, row, p):
            """Copy row `row` of a batch-N prefill buffer into a FRESH
            batch-1 buffer (the prefix-cache entry) valid through `p`
            tokens. No donation: the output is the newly-allocated entry
            and the source scratch stays pooled."""

            def take(arr):
                sizes = (arr.shape[0], 1) + arr.shape[2:]
                start = (0, row) + (0,) * (arr.ndim - 2)
                return jax.lax.dynamic_slice(arr, start, sizes)

            return KVCache(
                k=take(prefix.k), v=take(prefix.v),
                lengths=jnp.full((1,), p, jnp.int32),
                k_scale=take(prefix.k_scale) if self.kv_quant else None,
                v_scale=take(prefix.v_scale) if self.kv_quant else None,
            )

        def chunk_step(params, tokens, cache, seq_len):
            """Extend a batch-1 prefix cache by one prompt chunk. Attention
            runs the continuation path (absolute-position masking against
            the cache written by earlier chunks) — prefill_flash's
            empty-cache contract doesn't hold past chunk 0."""
            _, cache = trunk(params, tokens, cache, seq_lens=seq_len)
            return cache

        def chunk_final(params, tokens, cache, seq_len, last_idx,
                        temp, top_p, top_k, rng):
            """Last chunk: also project the final valid position and sample
            the first token (mirrors `prefill`'s tail)."""
            h, cache = trunk(params, tokens, cache, seq_lens=seq_len)
            h_last = jnp.take_along_axis(
                h, last_idx[:, None, None].astype(jnp.int32), axis=1)
            last = logits_from_hidden(params, cfg, h_last)[:, 0]
            toks = sample_tokens(last, rng, temp, top_p, top_k)
            return toks, cache

        def decode_one(state: DecodeState, params):
            """Advance every slot one token."""
            h, cache = trunk(params, state.last_token[:, None], state.cache)
            logits = logits_from_hidden(params, cfg, h)
            split = jax.vmap(lambda k: jax.random.split(k, 2))(state.rng)
            rng, step_key = split[:, 0], split[:, 1]
            toks = sample_tokens(logits[:, 0], step_key, state.temperature,
                                 state.top_p, state.top_k)
            return DecodeState(
                cache=cache, last_token=toks, temperature=state.temperature,
                top_p=state.top_p, top_k=state.top_k, rng=rng,
            ), toks

        def decode_block(params, state: DecodeState):
            """K decode steps in ONE dispatch. Host→device round-trips cost
            ~100ms here (remote chip); amortizing them K× is the difference
            between ~80 and >1000 tok/s aggregate (SURVEY §7 hard-part 3:
            streaming latency discipline). Returns (state, tokens [K, B])."""
            return jax.lax.scan(
                lambda s, _: decode_one(s, params), state, None,
                length=self.decode_block)

        def verify_block(params, state: DecodeState, draft, n_draft):
            """Speculative verify: ONE batched forward over [B, 1+k_draft]
            positions — the pending last_token plus every slot's drafted
            continuation — then per-position acceptance (ops/sampling.py
            verify_tokens) and a per-slot cache-length rollback to the
            first rejection. Fixed [B, 1+k] shape: exactly one compiled
            program, covered by warmup only when the knob is on.

            The trunk is the same continuation path chunk_step uses
            (absolute-position causal masking against the live cache), so
            KV for all 1+k positions is appended in place; positions past
            each slot's seq_len write garbage that the rollback lengths
            exclude and later writes overwrite — the rollback itself is
            one lengths update, no data movement. A slot with n_draft 0
            advances exactly one token, like a plain decode step."""
            tokens = jnp.concatenate([state.last_token[:, None], draft],
                                     axis=1)               # [B, 1+k]
            seq_lens = 1 + n_draft
            old_lengths = state.cache.lengths
            h, cache = trunk(params, tokens, state.cache, seq_lens=seq_lens)
            # Head over all 1+k positions: unlike prefill's bucket-wide
            # pad, every lane here is a candidate token — and 1+k is tiny.
            logits = logits_from_hidden(params, cfg, h)    # [B, 1+k, V]
            split = jax.vmap(lambda q: jax.random.split(q, 2))(state.rng)
            rng, step_key = split[:, 0], split[:, 1]
            out, n_emit = verify_tokens(
                logits, draft, n_draft, step_key, state.temperature,
                state.top_p, state.top_k)
            last = jnp.take_along_axis(out, (n_emit - 1)[:, None],
                                       axis=1)[:, 0]
            # Roll back: only the accepted prefix (and the pending bonus
            # token's future write position) stays valid.
            cache = cache._replace(lengths=old_lengths + n_emit)
            return DecodeState(
                cache=cache, last_token=last, temperature=state.temperature,
                top_p=state.top_p, top_k=state.top_k, rng=rng,
            ), out.T, n_emit

        state_shard = self._state_shardings
        if self.mesh is not None:
            # Host-read outputs (sampled tokens) must be fully replicated —
            # on a multi-process mesh np.asarray of a sharded global array
            # is not addressable. The prefill KV prefix keeps the cache's
            # kv_heads-on-model sharding; its batch dim (1) stays unsharded.
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self.mesh, P())
            # Same rules as the decode cache, minus the batch axis (the
            # prefix has batch 1) — derived from the shared rules table so
            # the layouts can't silently diverge (parallel/sharding.py).
            from symmetry_tpu.parallel.sharding import DEFAULT_RULES

            base_rules = self._rules or DEFAULT_RULES
            cax = cache_logical_axes(quantized=self.kv_quant)
            prefix_rules = {**base_rules, "batch": None}
            psc = (shardings_for(cax.k_scale, self.mesh, prefix_rules)
                   if self.kv_quant else None)
            prefix_shard = KVCache(
                k=shardings_for(cax.k, self.mesh, prefix_rules),
                v=shardings_for(cax.v, self.mesh, prefix_rules),
                lengths=rep,
                k_scale=psc, v_scale=psc,
            )
            self._prefix_shard = prefix_shard
            self._prefill = jax.jit(prefill, donate_argnums=(7,),
                                    out_shardings=(rep, prefix_shard))
            self._decode = jax.jit(decode_block, donate_argnums=(1,),
                                   out_shardings=(state_shard, rep))
            if self.spec is not None:
                self._verify = jax.jit(
                    verify_block, donate_argnums=(1,),
                    out_shardings=(state_shard, rep, rep))
            self._chunk_step = jax.jit(chunk_step, donate_argnums=(2,),
                                       out_shardings=prefix_shard)
            self._chunk_final = jax.jit(chunk_final, donate_argnums=(2,),
                                        out_shardings=(rep, prefix_shard))
            self._insert_from_blocks = jax.jit(
                insert_from_blocks, donate_argnums=(0,),
                out_shardings=prefix_shard)
            self._write_blocks = jax.jit(
                write_blocks, donate_argnums=(0,),
                out_shardings=prefix_shard)
            self._extract_prefix_row = jax.jit(
                extract_prefix_row, out_shardings=prefix_shard)
        else:
            self._prefill = jax.jit(prefill, donate_argnums=(7,))
            self._decode = jax.jit(decode_block, donate_argnums=(1,))
            if self.spec is not None:
                self._verify = jax.jit(verify_block, donate_argnums=(1,))
            self._chunk_step = jax.jit(chunk_step, donate_argnums=(2,))
            self._chunk_final = jax.jit(chunk_final, donate_argnums=(2,))
            self._insert_from_blocks = jax.jit(insert_from_blocks,
                                               donate_argnums=(0,))
            self._write_blocks = jax.jit(write_blocks, donate_argnums=(0,))
            self._extract_prefix_row = jax.jit(extract_prefix_row)
        self._insert_all = jax.jit(
            insert_all, donate_argnums=(0,),
            out_shardings=state_shard)

        def rng_resume(key, skip):
            """Fast-forward one request's PRNG chain past `skip` draws
            (stream resumption): replays the exact split sequence the
            serving path performs — prefill consumes the first split's
            key, every decode step re-splits the carry — so the returned
            (prefill key, decode key) put a resumed seeded request at
            the same chain position an uninterrupted run would occupy
            after `skip` sampled tokens. `skip` is DATA (fori_loop trip
            count), so one compiled program covers every resume depth —
            no per-length recompile."""
            pk, dk = jax.random.split(key)

            def body(_, carry):
                dk, _pk = carry
                s = jax.random.split(dk)
                return s[0], s[1]

            dk, pk = jax.lax.fori_loop(0, skip, body, (dk, pk))
            return pk, dk

        # Scalar key program, mesh-independent (keys are replicated).
        self._rng_resume = jax.jit(rng_resume)

    # ------------------------------------------------------------------
    # Host-side API (called by the scheduler's engine thread)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise EngineError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket ({self.prefill_buckets[-1]})")

    # Coalesced-prefill batch sizes: one compiled prefill program per
    # (batch, bucket) pair, so batch is bucketed too. The batch width is
    # gated PER BUCKET by a token budget (batch × bucket ≤ budget): wide
    # batches at the small buckets — a 128-client burst of 128-token
    # prompts is 8 dispatches at batch 16 instead of 32 at batch 4, the
    # direct driver of burst TTFT — while the big buckets stay narrow so
    # the transient prefill buffers never tip the HBM budget (round-2's
    # flat batch-8-at-every-bucket attempt OOM'd the llama3-8b@128-slot
    # config; batch 4 × 2048 tokens was the peak, not batch 8 × 128).
    PREFILL_BATCHES = (1, 2, 4, 8, 16)
    PREFILL_TOKEN_BUDGET = 2048

    def prefill_batches_for(self, bucket: int) -> tuple[int, ...]:
        """Allowed coalesced-prefill batch sizes at `bucket` (ascending,
        always contains 1). Capped by max_slots: a batch wider than the
        slot count could be SELECTED at runtime (next-largest padding) but
        is never compiled by warmup — the resulting mid-traffic XLA
        compile is the exact stall warmup exists to prevent."""
        budget = max(self.prefill_token_budget, bucket)
        return tuple(b for b in self.PREFILL_BATCHES
                     if b * bucket <= budget
                     and (b == 1 or b <= self.max_slots))

    def _request_keys(self, sampling: SamplingParams) -> tuple[Any, Any]:
        """(prefill key, decode key) for one request: seeded requests
        reproduce their whole completion; unseeded ones get per-request
        entropy. ONE derivation shared by every admission path, so a
        seeded request samples identically whether it was admitted via
        full prefill, chunked prefill, or a prefix-cache hit.

        `rng_skip` (stream resumption) fast-forwards a SEEDED request's
        chain past the draws its interrupted run already made: the
        uninterrupted run samples token 1 from the prefill key and token
        i+1 from the i-th decode split, so a resume after N emitted
        tokens needs prefill key = the N-th step key and decode key =
        the N-th carry — exactly what _rng_resume walks to."""
        skip = max(0, int(sampling.rng_skip or 0))
        if sampling.seed is not None:
            key = jax.random.key(sampling.seed)
            if skip:
                pk, dk = self._rng_resume(key, skip)
                return pk, dk
        else:
            self._requests_served += 1
            key = jax.random.fold_in(self._base_key, self._requests_served)
        pk, dk = jax.random.split(key)
        return pk, dk

    def prefill_and_insert(self, slot: int, prompt_ids: list[int],
                           sampling: SamplingParams) -> int:
        """Prefill a prompt and install it in `slot`; returns first token."""
        return self.prefill_and_insert_many(
            [(slot, prompt_ids, sampling)])[0]

    def prefill_and_insert_many(
        self, assignments: list[tuple[int, list[int], SamplingParams]],
    ) -> list[int]:
        """Prefill several prompts in as few device dispatches as the
        bucket's batch budget allows and install each in its slot; returns
        their first tokens. Coalescing matters because each dispatch pays
        a host↔device round-trip: admitting a burst of arrivals one-by-one
        serializes that cost into the last request's TTFT (SURVEY §7
        hard-part 3). A group wider than the bucket's largest allowed
        batch is split into consecutive dispatches."""
        if not assignments:
            return []
        if any(len(ids) == 0 for _, ids, _ in assignments):
            raise EngineError("empty prompt")
        n_req = len(assignments)
        bucket = max(self.bucket_for(len(ids)) for _, ids, _ in assignments)
        allowed = self.prefill_batches_for(bucket)
        if n_req > allowed[-1]:
            return [tok
                    for start in range(0, n_req, allowed[-1])
                    for tok in self.prefill_and_insert_many(
                        assignments[start:start + allowed[-1]])]
        batch = next(b for b in allowed if b >= n_req)

        padded = np.zeros((batch, bucket), np.int32)
        lens = np.zeros((batch,), np.int32)
        temps = np.zeros((batch,), np.float32)
        top_ps = np.ones((batch,), np.float32)
        top_ks = np.zeros((batch,), np.int32)
        prefill_keys, decode_keys = [], []
        slots_arr = np.zeros((batch,), np.int32)
        for i in range(batch):
            # Pad rows replay the last request BIT-IDENTICALLY — same
            # prompt, same slot, and (below) the same PRNG keys. They are
            # inserted (insert_all covers every row), so anything short of
            # an identical overwrite would corrupt the last real slot's
            # state: a pad row with fresh entropy would sample a DIFFERENT
            # first token and leave decode conditioned on a token the
            # client never saw.
            slot, ids, sampling = assignments[min(i, n_req - 1)]
            slots_arr[i] = slot
            padded[i, :len(ids)] = ids
            lens[i] = len(ids)
            temps[i] = sampling.temperature
            top_ps[i] = sampling.top_p
            top_ks[i] = sampling.top_k
            if i >= n_req:
                prefill_keys.append(prefill_keys[n_req - 1])
                decode_keys.append(decode_keys[n_req - 1])
                continue
            pk, dk = self._request_keys(sampling)
            prefill_keys.append(pk)
            decode_keys.append(dk)

        lens_arr = jnp.asarray(lens)
        temps_arr = jnp.asarray(temps)
        top_ps_arr = jnp.asarray(top_ps)
        top_ks_arr = jnp.asarray(top_ks)
        decode_keys_arr = jnp.stack(decode_keys)
        dp = self.devprof
        t_dp = dp.begin() if dp.enabled else 0.0
        toks, prefix = self._prefill(
            self.params, jnp.asarray(padded), lens_arr, temps_arr,
            top_ps_arr, top_ks_arr, jnp.stack(prefill_keys),
            self._prefill_scratch_for(batch, bucket))
        # One dispatch installs every row; pad rows re-write the last
        # real slot with bit-identical data (same prompt AND keys above).
        self.state = self._insert_all(
            self.state, prefix, jnp.asarray(slots_arr), lens_arr,
            toks, temps_arr, top_ps_arr, top_ks_arr, decode_keys_arr)
        if dp.enabled:
            # The probe covers the prefill + insert chain (device order
            # is FIFO, so last_token ready implies both executed).
            dp.probe("prefill", self.state.last_token, t_dp)
        # Populate the prefix cache from this batch BEFORE the buffer goes
        # back to the pool (the extract reads it; the next same-shape
        # prefill would overwrite it).
        if self.prefix_index is not None:
            self.prefix_index.note_miss(n_req)  # admitted uncached
            self._maybe_store_prefix(assignments[:n_req], prefix)
        # insert_all READS prefix (no donation): the buffer is free for
        # the next same-shape prefill the moment the insert executes —
        # device-order sequencing makes immediate reuse safe.
        self._store_prefill_scratch(batch, bucket, prefix)
        host_toks = np.asarray(toks)
        return [int(host_toks[i]) for i in range(n_req)]

    # ------------------------------------------------------------------
    # Shared-prefix KV cache (engine side; bookkeeping in prefix_cache.py)

    def prefix_lookup(self, prompt_ids: list[int]) -> RadixHit | None:
        """Pinned longest block-aligned prefix hit for this prompt, or
        None. The scheduler partitions admission groups by the hit's
        (node, matched_len) group key (hit/miss requests become separate
        dispatch units) and must release() hits it ends up not
        dispatching; the engine releases hits it consumes."""
        if self.prefix_index is None:
            return None
        return self.prefix_index.lookup(prompt_ids)

    def _bucket_ids(self, bucket: int, blocks=(), at: int = 0):
        """Padded block-id lane vector for one bucket's gather/scatter:
        lane j covers bucket positions [j*PB, (j+1)*PB). Lanes outside
        `blocks` (placed starting at block lane `at`) carry the trash
        block — gathers from it are never attended, scatters to it are
        never read. Fixed shape per bucket: ids are data, not shape."""
        ids = np.zeros((bucket // self.prefix_block,), np.int32)
        if len(blocks):
            ids[at:at + len(blocks)] = blocks
        return jnp.asarray(ids)

    def seeded_chunk_ok(self, prompt_len: int) -> bool:
        """True when a LONG-suffix hit (suffix > prefix_align) can run as
        a seeded chunked prefill: the chunk programs for this prompt's
        bucket exist only when the bucket exceeds one chunk (warmup
        compiles exactly that set). Otherwise the hit must fall back to a
        plain full prefill — never a mid-traffic XLA compile."""
        return (self.prefill_chunk is not None
                and self.bucket_for(prompt_len) > self.prefill_chunk)

    def prefill_and_insert_cached(
        self, assignments: list[tuple[int, list[int], SamplingParams]],
        hit: RadixHit,
    ) -> list[int]:
        """Admit a group of requests that SHARE a cached prefix: one
        block gather seeds every row of the (batch, bucket) working
        buffer straight from the pool, one continuation dispatch
        prefills only the uncached suffixes (<= prefix_align tokens
        each, the compiled suffix shape) and samples first tokens, one
        insert installs every slot — three dispatches for the whole
        group regardless of how long the shared prefix is. The finished
        rows then extend the radix tree with their NEW tail blocks, so
        the next turn of the same session hits at its full history.
        Releases `hit` in all paths."""
        try:
            if not assignments:
                return []
            p = hit.length
            A = self.prefix_align
            n_req = len(assignments)
            bucket = max(self.bucket_for(len(ids))
                         for _, ids, _ in assignments)
            allowed = self.prefill_batches_for(bucket)
            if n_req > allowed[-1]:
                raise EngineError(
                    f"cached-prefill group of {n_req} exceeds the bucket's "
                    f"batch cap {allowed[-1]} (scheduler partitions to cap)")
            for _, ids, _ in assignments:
                if not p < len(ids) <= p + A:
                    raise EngineError(
                        f"cached-prefill suffix out of range: prompt "
                        f"{len(ids)} vs prefix {p} (suffix cap {A})")
                if tuple(ids[:p]) != hit.tokens:
                    raise EngineError("prompt diverges from cached prefix")
            batch = next(b for b in allowed if b >= n_req)

            suffix = np.zeros((batch, A), np.int32)
            sfx_lens = np.zeros((batch,), np.int32)
            full_lens = np.zeros((batch,), np.int32)
            temps = np.zeros((batch,), np.float32)
            top_ps = np.ones((batch,), np.float32)
            top_ks = np.zeros((batch,), np.int32)
            slots_arr = np.zeros((batch,), np.int32)
            prefill_keys, decode_keys = [], []
            for i in range(batch):
                # Pad rows replay the last request bit-identically (same
                # suffix, slot, and keys) — same contract as the full
                # prefill path: every row is inserted, so a pad row must
                # be an exact overwrite of the last real slot.
                slot, ids, sampling = assignments[min(i, n_req - 1)]
                sfx = ids[p:]
                suffix[i, :len(sfx)] = sfx
                sfx_lens[i] = len(sfx)
                full_lens[i] = len(ids)
                temps[i] = sampling.temperature
                top_ps[i] = sampling.top_p
                top_ks[i] = sampling.top_k
                slots_arr[i] = slot
                if i >= n_req:
                    prefill_keys.append(prefill_keys[n_req - 1])
                    decode_keys.append(decode_keys[n_req - 1])
                    continue
                pk, dk = self._request_keys(sampling)
                prefill_keys.append(pk)
                decode_keys.append(dk)

            dp = self.devprof
            t_dp = dp.begin() if dp.enabled else 0.0
            scratch = self._prefill_scratch_for(batch, bucket)
            scratch = self._insert_from_blocks(
                scratch, self._pool_kv, self._bucket_ids(bucket, hit.blocks),
                jnp.int32(p))
            if dp.enabled:
                dp.probe("seed_gather", scratch.lengths, t_dp)
            # The gather out of the pool is dispatched (device order is
            # FIFO, so any later scatter into a since-freed block runs
            # after this read): safe to unpin now.
            hit.release()
            sfx_arr = jnp.asarray(sfx_lens)
            temps_arr = jnp.asarray(temps)
            top_ps_arr = jnp.asarray(top_ps)
            top_ks_arr = jnp.asarray(top_ks)
            decode_keys_arr = jnp.stack(decode_keys)
            t_dp = dp.begin() if dp.enabled else 0.0
            toks, prefix = self._chunk_final(
                self.params, jnp.asarray(suffix), scratch, sfx_arr,
                sfx_arr - 1, temps_arr, top_ps_arr, top_ks_arr,
                jnp.stack(prefill_keys))
            self.state = self._insert_all(
                self.state, prefix, jnp.asarray(slots_arr),
                jnp.asarray(full_lens), toks, temps_arr, top_ps_arr,
                top_ks_arr, decode_keys_arr)
            if dp.enabled:
                # The cached-hit suffix dispatch is still a prefill on
                # the device (chunk_final + insert over the seeded rows).
                dp.probe("prefill", self.state.last_token, t_dp)
            # The finished rows hold prefix + suffix KV: extend the tree
            # with the new tail blocks BEFORE the buffer goes back to
            # the scratch pool — this is what makes turn N+1 of a
            # session hit at its FULL history instead of re-prefilling
            # the part turn N added.
            self._maybe_store_prefix(assignments[:n_req], prefix)
            self._store_prefill_scratch(batch, bucket, prefix)
            self.prefix_index.note_reuse(n_req, p)
            host_toks = np.asarray(toks)
            return [int(host_toks[i]) for i in range(n_req)]
        finally:
            hit.release()

    def _maybe_store_prefix(self, assignments, prefix) -> None:
        """Store ONE newly-built prefix from a prefill batch into the
        pool (at most one extract + one scatter dispatch per admission
        dispatch, so cache population cannot balloon admission latency).
        The stored row is the first whose whole-block prefix has an
        unresident tail; only the NEW blocks are scattered — blocks the
        radix tree already holds stay shared by reference, and their
        scatter lanes point at the trash block."""
        PB = self.prefix_block
        for row, (_slot, ids, _sampling) in enumerate(assignments):
            p = PB * (len(ids) // PB)
            if p < PB:
                continue
            dp = self.devprof
            t_dp = 0.0
            plan = self.prefix_index.plan_insert(ids[:p])
            if plan is None:
                continue  # fully resident, or rejected even after LRU
            try:
                # Inside the try: a device failure in the extract (or
                # anywhere before commit) must abort the plan, or its
                # pinned prefix and allocated blocks leak forever. The
                # probe's begin() sits here too — only a path that
                # actually dispatches may close a pending dispatch gap
                # (a plan-None early-out closing it at a bookkeeping
                # moment would bias gap_share low), and an exception in
                # it must abort the plan like any other pre-commit
                # failure.
                if dp.enabled:
                    t_dp = dp.begin()
                row_cache = self._extract_prefix_row(
                    prefix, jnp.int32(row), jnp.int32(p))
                bucket = row_cache.k.shape[2]
                lane0 = plan.matched_len // PB
                self._pool_kv = self._write_blocks(
                    self._pool_kv, row_cache,
                    self._bucket_ids(bucket, plan.new_ids, at=lane0))
            except Exception:
                plan.abort()
                raise
            plan.commit()
            if dp.enabled:
                dp.probe("scatter", self._pool_kv.lengths, t_dp)
            return

    def prefix_cache_stats(self) -> dict | None:
        return (self.prefix_index.stats()
                if self.prefix_index is not None else None)

    def prefix_cache_summary(self) -> dict | None:
        """Compact radix-cache summary for pool gossip (see
        RadixIndex.summary) — recomputed at most every
        `prefix_gossip_s` seconds so per-member heartbeat probes share
        one walk. None when the cache or the gossip rider is off.
        Called from the host's serve (stats) thread; the summary walk
        itself is read-only and exception-guarded."""
        if self.prefix_index is None or self.prefix_gossip_blocks <= 0:
            return None
        now = time.monotonic()
        cached = self._gossip_cache
        if cached is not None and now - cached[0] < self.prefix_gossip_s:
            return cached[1]
        s = self.prefix_index.summary(self.prefix_gossip_blocks)
        self._gossip_cache = (now, s)
        return s

    # ------------------------------------------------------------------
    # Disaggregated prefill/decode (engine side; wire format and broker
    # in engine/disagg/)

    def kv_bytes_per_token(self) -> int:
        """Bytes of KV cache one token position occupies (k + v payloads
        plus scale planes when int8-quantized) — sizes handoff frames
        and the decode tier's adoption-budget floor."""
        c = self.config
        per_plane = c.num_layers * c.num_kv_heads
        if self.kv_quant:
            # int8 payload + one f32 scale per (layer, head, position)
            return 2 * per_plane * (c.dim_per_head + 4)
        return 2 * per_plane * c.dim_per_head * jnp.dtype(
            self.cache_dtype).itemsize

    def extract_slot_kv(self, slot: int, p: int):
        """Batch-1 snapshot of decode-lane `slot`'s KV, lengths pinned to
        `p` — the device half of a prefill-tier handoff. Every admission
        path (full prefill, chunked, prefix-cache hit) ends by inserting
        the prompt's KV into the slot lane, so extracting FROM the lane
        is uniform across all of them. Reuses the prefix-cache row
        extract (the decode state's cache is a KVCache with batch on dim
        1), then trims the position axis to the smallest prefill bucket
        holding `p` — the host→device→host transfer the caller pays must
        scale with the prompt, not max_seq_len (the trim is an eager
        slice, one cached variant per bucket; prefill-role warmup covers
        them). The caller np.asarray-syncs the result before the lane
        can be reused (the handoff sink runs on the engine thread, ahead
        of any next admission)."""
        if not 0 <= slot < self.max_slots:
            raise EngineError(f"extract_slot_kv: slot {slot} out of range")
        row = self._extract_prefix_row(self.state.cache, jnp.int32(slot),
                                       jnp.int32(p))
        cap = self.bucket_for(max(int(p), 1))
        if cap >= self.max_seq_len:
            return row

        def cut(arr, axis):
            return (jax.lax.slice_in_dim(arr, 0, cap, axis=axis)
                    if arr is not None else None)

        return row._replace(k=cut(row.k, 2), v=cut(row.v, 2),
                            k_scale=cut(row.k_scale, 3),
                            v_scale=cut(row.v_scale, 3))

    def adopt_prefix(self, handoff) -> bool:
        """Decode-tier adoption: a deserialized KV handoff (engine/
        disagg/frames.py KVHandoff, block-manifest format) lands in the
        radix tree, so the migrated request admits through the ordinary
        cached path — ONE block gather + ONE suffix dispatch, the same
        programs a local prefix hit uses.

        The frame carries per-block payloads plus a digest manifest;
        blocks the sender skipped (already shipped once) OR that this
        tree already holds adopt BY REFERENCE — only genuinely new
        blocks are assembled into one bucket-padded row and scattered
        into the pool in a single dispatch. The adopted prefix is the
        longest leading run of resident-or-shipped blocks (a skipped
        block this tier has since evicted just shortens the run — the
        request re-prefills a longer suffix, always causally sound).

        Returns True when a non-empty prefix is (or already was)
        resident, False when nothing could be adopted (routing-only
        frame, pool rejection) — the request then admits through a full
        prefill, which is slower but still token-identical for greedy.
        Structural mismatches between the frame and THIS engine's
        model/cache geometry raise: adopting wrong-shaped or
        wrong-dtype KV would stream garbage."""
        if self.prefix_index is None:
            raise EngineError("adopt_prefix requires the prefix cache "
                              "(role: decode builds it by contract)")
        p = int(handoff.p)
        if p <= 0:
            return False  # routing-only handoff: nothing to adopt
        PB = self.prefix_block
        bs = int(handoff.block_size)
        if p % bs:
            raise EngineError(f"handoff prefix length {p} is not a "
                              f"multiple of its block size {bs}")
        if bool(handoff.kv_quant) != bool(self.kv_quant):
            raise EngineError(
                f"handoff KV quantization ({handoff.kv_quant}) disagrees "
                f"with this engine ({self.kv_quant}) — tiers must share "
                f"the cache layout")
        c = self.config
        want = (c.num_layers, 1, bs, c.num_kv_heads, c.dim_per_head)
        want_dtype = np.dtype(np.int8 if self.kv_quant
                              else self.cache_dtype)
        for j, planes in handoff.blocks.items():
            k, v = planes["k"], planes["v"]
            if k.shape != want or v.shape != want:
                raise EngineError(
                    f"handoff block {j} KV shape {k.shape} does not "
                    f"match this model ({want})")
            if k.dtype != want_dtype or v.dtype != want_dtype:
                raise EngineError(
                    f"handoff block {j} KV dtype {k.dtype} does not "
                    f"match this engine's cache dtype {want_dtype}")
        tokens = tuple(int(t) for t in handoff.tokens[:p])
        # Leading coverage: resident tree blocks first, then contiguous
        # shipped frame blocks. A hole (skipped-and-evicted) ends it.
        cov = self.prefix_index.match_len(tokens)
        for j in range(p // bs):
            lo, hi = j * bs, (j + 1) * bs
            if hi <= cov:
                continue
            if lo > cov or j not in handoff.blocks:
                break
            cov = hi
        p_eff = PB * (min(cov, p) // PB)
        if p_eff <= 0:
            return False
        dp = self.devprof
        t_dp = 0.0
        plan = self.prefix_index.plan_insert(tokens[:p_eff])
        if plan is None:
            # Fully resident (adoption by reference — the sender skipped
            # everything and this tree still holds it), or the pool
            # rejected the tail even after eviction.
            return self.prefix_index.match_len(tokens[:p_eff]) >= p_eff
        # Assemble the new tail into one bucket-padded batch-1 row and
        # scatter it in ONE dispatch — the same per-bucket program the
        # local store path compiled, so adoption never triggers a
        # mid-traffic XLA compile. The whole assembly runs inside the
        # try: a failure anywhere between plan and commit (no bucket
        # fits, a frame missing its scale planes, a device transfer
        # error) must abort the plan, or its pinned matched prefix and
        # allocated blocks leak forever. The probe's begin() sits inside
        # for the same two reasons as _maybe_store_prefix: only a path
        # that dispatches may close a pending dispatch gap, and an
        # exception in it must abort the plan.
        try:
            if dp.enabled:
                t_dp = dp.begin()
            capacity = self.bucket_for(p_eff)
            m = plan.matched_len
            k_row = np.zeros((c.num_layers, 1, capacity, c.num_kv_heads,
                              c.dim_per_head), want_dtype)
            v_row = np.zeros_like(k_row)
            ks_row = vs_row = None
            if self.kv_quant:
                ks_row = np.zeros(
                    (c.num_layers, 1, c.num_kv_heads, capacity),
                    np.float32)
                vs_row = np.zeros_like(ks_row)
            for j, planes in handoff.blocks.items():
                lo, hi = j * bs, (j + 1) * bs
                if hi <= m or lo >= p_eff:
                    continue  # resident already, or past the adopted run
                # A frame block may straddle p_eff when the sender's
                # block size is not a multiple of this pool's (the
                # floored tail): clip to the adopted run — the row is
                # only capacity wide.
                w = min(hi, p_eff) - lo
                k_row[:, :, lo:lo + w] = planes["k"][:, :, :w]
                v_row[:, :, lo:lo + w] = planes["v"][:, :, :w]
                if self.kv_quant:
                    ks_row[:, :, :, lo:lo + w] = \
                        planes["k_scale"][:, :, :, :w]
                    vs_row[:, :, :, lo:lo + w] = \
                        planes["v_scale"][:, :, :, :w]
            row = KVCache(
                k=jnp.asarray(k_row), v=jnp.asarray(v_row),
                lengths=jnp.full((1,), p_eff, jnp.int32),
                k_scale=jnp.asarray(ks_row) if self.kv_quant else None,
                v_scale=jnp.asarray(vs_row) if self.kv_quant else None,
            )
            self._pool_kv = self._write_blocks(
                self._pool_kv, row,
                self._bucket_ids(capacity, plan.new_ids, at=m // PB))
        except Exception:
            plan.abort()
            raise
        plan.commit()
        if dp.enabled:
            # Adoption's device work: host→device row transfer + the
            # one-dispatch pool scatter.
            dp.probe("adopt", self._pool_kv.lengths, t_dp)
        return True

    # ------------------------------------------------------------------
    # Chunked prefill (long prompts, interleaved with decode blocks)

    def wants_chunked(self, prompt_len: int) -> bool:
        """True when this prompt should prefill chunk-by-chunk: more than
        one chunk long (a single-chunk prompt IS one dispatch already)."""
        return (self.prefill_chunk is not None
                and prompt_len > self.prefill_chunk)

    def start_chunked_prefill(self, slot: int, prompt_ids: list[int],
                              sampling: SamplingParams,
                              hit: RadixHit | None = None) -> ChunkedPrefill:
        """Begin a chunked prefill for `slot`; drive it to completion with
        advance_chunked_prefill (one device dispatch per call). With a
        prefix-cache `hit`, the cache is seeded from the cached entry and
        the chunk loop covers only the uncached suffix (the long-suffix
        hit path — suffixes <= prefix_align go through
        prefill_and_insert_cached in one dispatch instead). The hit is
        released here in all paths."""
        try:
            if not prompt_ids:
                raise EngineError("empty prompt")
            C = self.prefill_chunk
            assert C is not None
            true_len = len(prompt_ids)
            bucket = self.bucket_for(true_len)  # validates length; cache size
            start = 0
            if hit is not None:
                start = hit.length
                if not 0 < start < true_len:
                    raise EngineError("cached prefix does not fit prompt")
                if tuple(prompt_ids[:start]) != hit.tokens:
                    raise EngineError("prompt diverges from cached prefix")
            sfx_len = true_len - start
            n_chunks = -(-sfx_len // C)
            padded = np.zeros((1, n_chunks * C), np.int32)
            padded[0, :sfx_len] = prompt_ids[start:]

            pk, dk = self._request_keys(sampling)

            cache = self._new_prefix_cache(bucket)
            if hit is not None:
                dp = self.devprof
                t_dp = dp.begin() if dp.enabled else 0.0
                cache = self._insert_from_blocks(
                    cache, self._pool_kv,
                    self._bucket_ids(bucket, hit.blocks), jnp.int32(start))
                if dp.enabled:
                    dp.probe("seed_gather", cache.lengths, t_dp)
                hit.release()  # gather dispatched; blocks free to evict
                self.prefix_index.note_reuse(1, start)
            elif self.prefix_index is not None:
                self.prefix_index.note_miss(1)  # admitted uncached
            return ChunkedPrefill(
                slot=slot, ids=padded, true_len=true_len, n_chunks=n_chunks,
                cache=cache,
                temp=jnp.asarray([sampling.temperature], jnp.float32),
                top_p=jnp.asarray([sampling.top_p], jnp.float32),
                top_k=jnp.asarray([sampling.top_k], jnp.int32),
                prefill_key=pk[None], decode_key=dk[None],
                start_pos=start, full_ids=tuple(prompt_ids),
            )
        finally:
            if hit is not None:
                hit.release()

    def advance_chunked_prefill(self, job: ChunkedPrefill) -> int | None:
        """Run ONE chunk; returns the first sampled token when the prompt
        is complete (the slot is then live), else None. Chunk offsets are
        relative to the SUFFIX the job carries — with a seeded start_pos
        the cache lengths already position the writes past the prefix."""
        C = self.prefill_chunk
        c0 = job.done_chunks * C
        chunk = jnp.asarray(job.ids[:, c0:c0 + C])
        valid = jnp.asarray([min(C, job.suffix_len - c0)], jnp.int32)
        last = job.done_chunks == job.n_chunks - 1
        dp = self.devprof
        t_dp = dp.begin() if dp.enabled else 0.0
        if not last:
            job.cache = self._chunk_step(self.params, chunk, job.cache,
                                         valid)
            job.done_chunks += 1
            if dp.enabled:
                dp.probe("chunk", job.cache.lengths, t_dp)
            return None
        last_idx = jnp.asarray([job.suffix_len - 1 - c0], jnp.int32)
        toks, cache = self._chunk_final(
            self.params, chunk, job.cache, valid, last_idx,
            job.temp, job.top_p, job.top_k, job.prefill_key)
        job.done_chunks += 1
        job.cache = None  # old buffer was donated to chunk_final; poison reuse
        # same (batch=1, bucket) insert program the prefill warmup grid
        # compiled — no chunk-specific insert compile
        self.state = self._insert_all(
            self.state, cache, jnp.asarray([job.slot], jnp.int32),
            jnp.asarray([job.true_len], jnp.int32), toks,
            job.temp, job.top_p, job.top_k, job.decode_key)
        if dp.enabled:
            dp.probe("chunk", self.state.last_token, t_dp)
        # The finished buffer holds the FULL prompt's KV — scatter its
        # unresident whole blocks into the pool before it is dropped.
        # Completed chunked prefills are exactly the long shared
        # preambles worth caching, and only the NEW tail is written:
        # blocks the tree already holds (e.g. the seed prefix of a
        # seeded job) stay shared by reference.
        if self.prefix_index is not None and job.full_ids:
            PB = self.prefix_block
            p = PB * (job.true_len // PB)
            plan = (self.prefix_index.plan_insert(job.full_ids[:p])
                    if p >= PB else None)
            if plan is not None:
                try:
                    bucket = cache.k.shape[2]
                    self._pool_kv = self._write_blocks(
                        self._pool_kv, cache,
                        self._bucket_ids(bucket, plan.new_ids,
                                         at=plan.matched_len // PB))
                except Exception:
                    plan.abort()
                    raise
                plan.commit()
        return int(np.asarray(toks)[0])

    def _new_prefix_cache(self, capacity: int, batch: int = 1):
        """Fresh batch-N prefix cache, created sharded-in-place (jit with
        out_shardings) so multi-process meshes work like _init_state."""
        c = self.config

        def make():
            return init_cache(c, batch, capacity, self.cache_dtype,
                              quantized=self.kv_quant)

        if self.mesh is not None:
            return jax.jit(make, out_shardings=self._prefix_shard)()
        return jax.jit(make)()

    def _prefill_scratch_for(self, batch: int, bucket: int):
        """The persistent prefix buffer for this (batch, bucket) prefill
        shape — donated through each prefill dispatch and stored back, so
        a shape in active use performs no HBM allocation (see `prefill`
        in _build_jits)."""
        key = (batch, bucket)
        scratch = self._prefill_scratch.pop(key, None)
        if scratch is None:
            scratch = self._new_prefix_cache(bucket, batch)
        return scratch

    def _store_prefill_scratch(self, batch: int, bucket: int,
                               prefix) -> None:
        """Return a prefix buffer to the pool, LRU-bounded: retaining
        EVERY (batch, bucket) grid shape would pin ~5x the token budget
        in KV lanes permanently (~630 MB for the default three-bucket
        llama3-8b grid) — worse steady-state pressure than the per-
        dispatch churn the pool exists to remove. The cap keeps the
        shapes actually in use warm (a serving workload concentrates on
        one or two) and lets rare shapes churn their small buffers."""
        key = (batch, bucket)
        self._prefill_scratch.pop(key, None)
        self._prefill_scratch[key] = prefix  # most-recently-used last
        cap = 2 * max(self.prefill_token_budget,
                      batch * bucket)
        total = sum(b * bk for (b, bk) in self._prefill_scratch)
        for old_key in list(self._prefill_scratch):
            if total <= cap or old_key == key:
                continue
            self._prefill_scratch.pop(old_key)  # dropped ref frees HBM
            total -= old_key[0] * old_key[1]

    def release_slot(self, slot: int) -> None:
        """A finished slot's cache lane is garbage until reuse (insert
        resets it); nothing to do device-side — the hook exists so the
        scheduler's slot lifecycle has a single engine-visible seam."""

    def warmup(self) -> None:
        """Compile every serving program before traffic: decode, and the
        full (PREFILL_BATCHES × prefill_buckets) prefill/insert grid. A
        fresh XLA compile mid-traffic (~30 s on a real chip) would stall
        every active stream — the first coalesced burst must not pay it.
        Call before the first insert — warmup advances device state with
        garbage that is only harmless on an empty cache.

        Role gating (two-tier warmup is the structural win of disagg): a
        "prefill" engine never decodes, so the decode block, the
        concurrent decode+prefill peak probe, and the speculative verify
        program are all skipped — its compile set is the prefill grid,
        the chunk programs, the prefix-cache paths, and ONE extract
        variant for the handoff snapshot. "decode"/"unified" compile the
        full set ("decode" has the prefix store on by contract, so the
        adoption seed-copy shapes are always covered)."""
        decode_side = self.role != "prefill"
        # The resume RNG fast-forward (scalar key program, one compile
        # covers every resume depth): warm it so the first mid-stream
        # recovery under load never pays a fresh XLA compile.
        self._rng_resume(jax.random.key(0), 0)
        if decode_side:
            self.state, _ = self._decode(self.params, self.state)
        for bucket in self.prefill_buckets:
            for batch in self.prefill_batches_for(bucket):
                if batch > self.max_slots:
                    continue
                toks, prefix = self._prefill(
                    self.params, jnp.zeros((batch, bucket), jnp.int32),
                    jnp.ones((batch,), jnp.int32),
                    jnp.zeros((batch,), jnp.float32),
                    jnp.ones((batch,), jnp.float32),
                    jnp.zeros((batch,), jnp.int32),
                    jax.random.split(jax.random.key(0), batch),
                    self._prefill_scratch_for(batch, bucket))
                self._store_prefill_scratch(batch, bucket, prefix)
                # insert_all compiles per (batch, bucket) too; slot 0
                # with true_len 0 leaves the state semantically untouched.
                self.state = self._insert_all(
                    self.state, prefix, jnp.zeros((batch,), jnp.int32),
                    jnp.zeros((batch,), jnp.int32), toks,
                    jnp.zeros((batch,), jnp.float32),
                    jnp.ones((batch,), jnp.float32),
                    jnp.zeros((batch,), jnp.int32),
                    jax.random.split(jax.random.key(0), batch))
        # Exercise the CONCURRENT decode+prefill peak once PER BUCKET:
        # serving overlaps an in-flight decode block with a prefill
        # dispatch, and their workspaces coexist in HBM — a configuration
        # that fits each program alone can still OOM at first traffic
        # (observed on a ~95%-full chip: warmup green, first burst
        # prefill RESOURCE_EXHAUSTED 3 s later). Every bucket's widest
        # batch is probed because the peak transient lives at the LARGE
        # buckets (round-2's OOM was batch 4 × 2048, not 16 × 128).
        # Failing HERE turns a mid-traffic wedge into a clean startup
        # failure the caller can react to. Side benefit, measured: the
        # overlapped-execution path is warmed, so in-serving admission
        # dispatches stop paying a first-overlap cost (admit p99 2.5 s →
        # 0.4 s, burst ramp 5.9 s → 4.3 s).
        for bucket in (self.prefill_buckets if decode_side else ()):
            widest = max(b for b in self.prefill_batches_for(bucket)
                         if b <= self.max_slots)
            pending = self._decode(self.params, self.state)
            self.state = pending[0]
            toks, prefix = self._prefill(
                self.params,
                jnp.zeros((widest, bucket), jnp.int32),
                jnp.ones((widest,), jnp.int32),
                jnp.zeros((widest,), jnp.float32),
                jnp.ones((widest,), jnp.float32),
                jnp.zeros((widest,), jnp.int32),
                jax.random.split(jax.random.key(0), widest),
                self._prefill_scratch_for(widest, bucket))
            self._store_prefill_scratch(widest, bucket, prefix)
            # Sync on the PREFILL output: the device queue is FIFO, so
            # its completion implies the decode's too — and JAX surfaces
            # async failures only on the poisoned output, so syncing the
            # decode alone would let a prefill OOM stay pending until
            # first traffic.
            np.asarray(toks)

        # Chunked-prefill programs: one (step, final) pair per bucket that
        # can hold a multi-chunk prompt. A mid-traffic compile would be the
        # exact stall chunking exists to prevent.
        C = self.prefill_chunk
        if C is not None:
            one = jnp.ones((1,), jnp.int32)
            for bucket in self.prefill_buckets:
                if bucket <= C:
                    continue
                cache = self._new_prefix_cache(bucket)
                cache = self._chunk_step(
                    self.params, jnp.zeros((1, C), jnp.int32), cache, one)
                toks, cache = self._chunk_final(
                    self.params, jnp.zeros((1, C), jnp.int32), cache, one,
                    jnp.zeros((1,), jnp.int32),
                    jnp.zeros((1,), jnp.float32), jnp.ones((1,), jnp.float32),
                    jnp.zeros((1,), jnp.int32),
                    jax.random.split(jax.random.key(0), 1))
                # batch-1 insert at this bucket already compiled above

        # Speculative verify program (only when the knob is on — off keeps
        # warmup's compile set byte-identical): exactly ONE extra compile,
        # the fixed [B, 1+k_draft] verify shape. Zero drafts advance every
        # lane one garbage token — harmless on the pre-insert empty cache,
        # same contract as the decode warmup above. The sync inside
        # verify_step surfaces a marginal-HBM failure at startup.
        if self.spec is not None and decode_side:
            self.verify_step(
                np.zeros((self.max_slots, self.spec.k_draft), np.int32),
                np.zeros((self.max_slots,), np.int32))

        if self.role == "prefill":
            # The handoff snapshot programs: the decode-state cache IS a
            # KVCache (batch on dim 1), so the prefix-cache row extract
            # serves as the slot-lane extract — one compiled variant —
            # plus one eager bucket-trim slice per prefill bucket. The
            # final sync doubles as the prefill-role startup-OOM probe
            # (the grid loop above dispatches without syncing).
            for bucket in self.prefill_buckets:
                np.asarray(self.extract_slot_kv(0, min(
                    bucket, self.max_seq_len)).lengths)

        # Prefix-cache hit-path programs (only when the cache is on —
        # budget 0 keeps warmup exactly as before): per bucket the block
        # scatter (store/adopt path), per (batch, bucket) the row
        # extract (store path), the block-gather seed, and the batched
        # suffix continuation at the prefix_align shape. A hit burst
        # mid-traffic must never pay a fresh XLA compile — the exact
        # stall the cache exists to remove. (The old aligned store
        # needed a seed-copy variant per entry CAPACITY on top of the
        # grid; pool blocks are all one shape, so that whole compile
        # dimension is gone.)
        if self.prefix_index is not None:
            A = self.prefix_align
            for bucket in self.prefill_buckets:
                # All lanes at the trash block: the scatter compiles and
                # runs, and the garbage lands where nobody reads.
                row = self._new_prefix_cache(bucket)
                self._pool_kv = self._write_blocks(
                    self._pool_kv, row, self._bucket_ids(bucket))
            for bucket in self.prefill_buckets:
                for batch in self.prefill_batches_for(bucket):
                    scratch = self._prefill_scratch_for(batch, bucket)
                    self._extract_prefix_row(scratch, jnp.int32(0),
                                             jnp.int32(0))
                    scratch = self._insert_from_blocks(
                        scratch, self._pool_kv, self._bucket_ids(bucket),
                        jnp.int32(0))
                    toks, prefix = self._chunk_final(
                        self.params, jnp.zeros((batch, A), jnp.int32),
                        scratch, jnp.ones((batch,), jnp.int32),
                        jnp.zeros((batch,), jnp.int32),
                        jnp.zeros((batch,), jnp.float32),
                        jnp.ones((batch,), jnp.float32),
                        jnp.zeros((batch,), jnp.int32),
                        jax.random.split(jax.random.key(0), batch))
                    self._store_prefill_scratch(batch, bucket, prefix)
                    # Sync so a marginal-HBM failure surfaces at startup,
                    # not at the first hit burst (same rationale as the
                    # concurrent-peak probe above).
                    np.asarray(toks)

        # Dispatch-cache closure. Donation aliases output buffers to the
        # donated inputs, so a state array's PHYSICAL provenance (which
        # executable originally materialized its buffer) survives across
        # program boundaries — and jaxlib's C++ fastpath keys on it. A
        # state that flowed insert→decode→insert therefore dispatches
        # under a different cache key than warmup's init→insert chain,
        # even though every aval, sharding, and layout compares equal:
        # the first serving burst grows _cache_size() without tracing or
        # compiling anything. compile_cache_sizes() is the steady-state
        # recompile tripwire (tests assert it stays flat under traffic),
        # so warmup must populate those signature classes too: run real
        # serving-shaped rounds — back-to-back inserts, decode-interleaved
        # inserts, consecutive decodes — until the per-program variant
        # counts reach a fixed point. The provenance-class graph is finite
        # (one class per materializing executable), so this converges in
        # a couple of rounds; every dispatch hits an already-compiled
        # program, so the cost is a handful of device launches, not
        # compiles.
        if decode_side:
            def _settle_insert(state, batch: int, bucket: int):
                toks, prefix = self._prefill(
                    self.params, jnp.zeros((batch, bucket), jnp.int32),
                    jnp.ones((batch,), jnp.int32),
                    jnp.zeros((batch,), jnp.float32),
                    jnp.ones((batch,), jnp.float32),
                    jnp.zeros((batch,), jnp.int32),
                    jax.random.split(jax.random.key(0), batch),
                    self._prefill_scratch_for(batch, bucket))
                self._store_prefill_scratch(batch, bucket, prefix)
                return self._insert_all(
                    state, prefix, jnp.zeros((batch,), jnp.int32),
                    jnp.zeros((batch,), jnp.int32), toks,
                    jnp.zeros((batch,), jnp.float32),
                    jnp.ones((batch,), jnp.float32),
                    jnp.zeros((batch,), jnp.int32),
                    jax.random.split(jax.random.key(0), batch))

            for _ in range(6):
                sizes = self.compile_cache_sizes()
                for bucket in self.prefill_buckets:
                    for batch in self.prefill_batches_for(bucket):
                        if batch > self.max_slots:
                            continue
                        # burst admission: inserts back-to-back
                        self.state = _settle_insert(self.state, batch,
                                                    bucket)
                        # steady decode between admissions
                        self.state, _ = self._decode(self.params, self.state)
                        self.state = _settle_insert(self.state, batch,
                                                    bucket)
                    # consecutive decode blocks (no admission between)
                    self.state, _ = self._decode(self.params, self.state)
                    self.state, _ = self._decode(self.params, self.state)
                if self.spec is not None:
                    self.verify_step(
                        np.zeros((self.max_slots, self.spec.k_draft),
                                 np.int32),
                        np.zeros((self.max_slots,), np.int32))
                if self.compile_cache_sizes() == sizes:
                    break

    def verify_step_dispatch(self, draft: np.ndarray, n_draft: np.ndarray
                             ) -> tuple[jax.Array, jax.Array]:
        """Dispatch ONE speculative verify WITHOUT syncing: `draft`
        [B, k_draft] holds each slot's proposed continuation tokens,
        `n_draft` [B] how many are real (0 = no proposal; the slot
        advances one plain token). Returns (tokens [1+k, B], n_emit [B])
        as device futures — the scheduler parks them in its pipeline and
        syncs them an iteration later, so admission/emit host work
        overlaps the verify's device execution exactly like a plain
        decode block (pre-pipeline, the same-iteration sync ate the
        overlap). The next PROPOSAL still waits for the sync: drafts are
        built from this dispatch's output."""
        if self.spec is None:
            raise EngineError("speculative decoding is not enabled")
        k = self.spec.k_draft
        if draft.shape != (self.max_slots, k):
            raise EngineError(
                f"draft shape {draft.shape} != {(self.max_slots, k)}")
        dp = self.devprof
        t_dp = dp.begin() if dp.enabled else 0.0
        self.state, toks, n_emit = self._verify(
            self.params, self.state, jnp.asarray(draft, jnp.int32),
            jnp.asarray(n_draft, jnp.int32))
        if dp.enabled:
            dp.probe("verify", toks, t_dp)
        return toks, n_emit

    def verify_step(self, draft: np.ndarray, n_draft: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Synchronous verify: dispatch + host transfer in one call
        (tests and non-pipelined callers). tokens[:n, b] with
        n = n_emit[b] are slot b's emitted run for this dispatch."""
        toks, n_emit = self.verify_step_dispatch(draft, n_draft)
        return np.asarray(toks), np.asarray(n_emit)

    def decode_steps_dispatch(self) -> jax.Array:
        """Dispatch one decode block WITHOUT syncing: returns the [K, B]
        device token array as a future. JAX async dispatch lets the caller
        enqueue block N+1 and only then block on block N's tokens, so the
        host-side work (transfer, detokenize, emit) overlaps block N+1's
        device execution (SURVEY §7 hard-part 3: double-buffered token
        fetch).

        A firing symprof probe (tpu.profile_sample) deliberately syncs
        THIS dispatch before returning — draining the pipeline is what
        makes the following dispatch gap a true device-idle sample; the
        1-in-N cadence bounds the serialization cost."""
        dp = self.devprof
        t_dp = dp.begin() if dp.enabled else 0.0
        self.state, toks = self._decode(self.params, self.state)
        if dp.enabled:
            dp.probe("decode_block", toks, t_dp)
        return toks

    def decode_steps(self) -> np.ndarray:
        """decode_block tokens for every slot; host gets [K, B] int32."""
        return np.asarray(self.decode_steps_dispatch())

    def decode_step(self) -> np.ndarray:
        """One decode step [B] (requires decode_block == 1; tests/bench)."""
        assert self.decode_block == 1, "decode_step needs decode_block=1"
        return self.decode_steps()[0]

    def slot_length(self, slot: int) -> int:
        return int(self.state.cache.lengths[slot])

    @property
    def slot_capacity(self) -> int:
        return self.max_seq_len

    def weight_stream_bytes(self) -> int:
        """Bytes of parameter data one decode step must stream from HBM:
        every matmul weight (int8 payload + f32 scales, or dense) is read
        in full each step — the decode-floor denominator (BASELINE.md
        convert-wall study). The input embedding is excluded unless tied:
        it is gathered (B rows), not contracted; tied models re-read it
        as the LM head. Metadata-only (nbytes), safe from any thread."""
        total = sum(leaf.nbytes for leaf in jax.tree.leaves(self.params))
        if not self.config.tie_embeddings:
            total -= self.params["embed"].nbytes
        return total

    def weight_stream_bytes_per_device(self) -> int:
        """Per-device slice of weight_stream_bytes: each leaf counts its
        LOCAL shard size (sharding.shard_shape), so TP sharded leaves
        divide by the axis size while replicated leaves count in full on
        every device — the actual per-chip HBM stream one decode step
        costs, and the denominator bench.py's per-device
        weight_stream_gbs reports. Metadata-only, safe from any thread;
        on a single device this equals weight_stream_bytes."""

        def local_nbytes(leaf) -> int:
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                return leaf.nbytes
            shard = sharding.shard_shape(leaf.shape)
            n = leaf.dtype.itemsize
            for d in shard:
                n *= d
            return n

        total = sum(local_nbytes(leaf)
                    for leaf in jax.tree.leaves(self.params))
        if not self.config.tie_embeddings:
            total -= local_nbytes(self.params["embed"])
        return total

    def compile_cache_sizes(self) -> dict[str, int]:
        """Compiled-variant count per jitted primitive. Warmup fills
        these; steady-state serving must never grow them — a mid-traffic
        XLA compile is the stall every warmup path exists to prevent
        (tests assert zero steady-state recompiles against this)."""
        out: dict[str, int] = {}
        for name in ("_prefill", "_decode", "_verify", "_chunk_step",
                     "_chunk_final", "_insert_all", "_insert_from_blocks",
                     "_write_blocks", "_extract_prefix_row"):
            fn = getattr(self, name, None)
            if fn is not None and hasattr(fn, "_cache_size"):
                out[name] = fn._cache_size()
        return out

    # ------------------------------------------------------------------

    @classmethod
    def from_tpu_config(cls, tpu_cfg: Any, *, platform_devices=None
                        ) -> "InferenceEngine":
        """Build from a provider.yaml `tpu:` section (provider/config.py).

        With `tpu.multihost` set, joins the jax.distributed job first and
        builds the hybrid DCN×ICI mesh over the GLOBAL device set — every
        process (rank 0 and workers) constructs the engine identically.
        """
        mesh_spec = MeshSpec.from_dict(tpu_cfg.mesh)
        if tpu_cfg.multihost:
            from symmetry_tpu.parallel.multihost import (
                build_multihost_mesh, init_distributed)

            mh = tpu_cfg.multihost
            init_distributed(mh["coordinator"], mh["num_processes"],
                             mh.get("process_id", 0))
            mesh = build_multihost_mesh(mesh_spec, mh.get("dcn_data", 1))
        else:
            devices = platform_devices or jax.devices()
            mesh = build_mesh(mesh_spec, devices) if mesh_spec.size > 1 else None

        dtypes = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                  "float16": jnp.float16}
        if tpu_cfg.dtype not in dtypes:
            raise EngineError(f"unsupported tpu.dtype {tpu_cfg.dtype!r}; "
                              f"expected one of {sorted(dtypes)}")
        dtype = dtypes[tpu_cfg.dtype]

        if tpu_cfg.quantization not in (None, "int8"):
            raise EngineError(
                f"unsupported tpu.quantization {tpu_cfg.quantization!r}")
        if tpu_cfg.kv_quantization not in (None, "int8"):
            raise EngineError(
                f"unsupported tpu.kv_quantization {tpu_cfg.kv_quantization!r}")
        quant = tpu_cfg.quantization == "int8"

        # Pipeline mode (mesh stage > 1): params shard their layer dim over
        # the stage axis instead of replicating it.
        rules = _stage_rules(mesh)

        if tpu_cfg.checkpoint_path:
            from symmetry_tpu.engine.weights import (
                load_checkpoint, load_warm_cache, save_warm_cache)
            from symmetry_tpu.utils.logging import logger

            # Warm restart (SURVEY §5.4): the finished tree — stacked,
            # transposed, quantized — is cached beside the checkpoint on
            # first load; restarts mmap it straight to device.
            warm = None
            # Single-process only, for BOTH directions: on a multi-host
            # mesh, a cache present on some hosts but not others would
            # send processes down divergent load paths and hang the first
            # cross-host collective.
            use_warm = (getattr(tpu_cfg, "warm_cache", True)
                        and jax.process_count() == 1)
            if use_warm:
                try:
                    warm = load_warm_cache(
                        tpu_cfg.checkpoint_path, dtype=dtype,
                        quantize=quant, mesh=mesh, rules=rules)
                except Exception as exc:  # noqa: BLE001 — cache is advisory
                    logger.warning(f"warm cache unreadable, cold load: {exc}")
            if warm is not None:
                params, config = warm
                logger.info("weights loaded from warm cache")
            else:
                params, config = load_checkpoint(
                    tpu_cfg.checkpoint_path, mesh=mesh, rules=rules,
                    dtype=dtype)
                if quant:
                    from symmetry_tpu.models.llama import quantize_params

                    params = quantize_params(params)
                if use_warm:
                    try:
                        save_warm_cache(tpu_cfg.checkpoint_path, params,
                                        config, dtype=dtype, quantize=quant)
                        logger.info("warm weight cache written")
                    except Exception as exc:  # noqa: BLE001
                        logger.warning(f"warm cache not written: {exc}")
        else:
            config = preset(tpu_cfg.model_preset or "tiny")
            if mesh is not None:
                from symmetry_tpu.models.llama import param_logical_axes

                # Initialize directly as global sharded arrays (works when
                # the mesh spans processes; device_put of host values
                # cannot). Quantized leaves init int8 in the same program.
                axes = param_logical_axes(config)
                if quant:
                    from symmetry_tpu.models.llama import (
                        quantized_logical_axes)

                    axes = quantized_logical_axes(axes)
                shardings = shardings_for(axes, mesh, rules)
                params = jax.jit(
                    lambda: init_params(config, jax.random.key(0), dtype,
                                        quantize=quant),
                    out_shardings=shardings)()
            else:
                params = init_params(config, jax.random.key(0), dtype,
                                     quantize=quant)
        # Tokenizer after config resolution: the byte fallback must span
        # the MODEL's vocab or sampled ids stream as silence (tokenizer.py).
        tokenizer = get_tokenizer(tpu_cfg.tokenizer_path,
                                  vocab_size=config.vocab_size)
        return cls(
            config, params, tokenizer, mesh=mesh,
            max_slots=tpu_cfg.max_batch_size,
            max_seq_len=tpu_cfg.max_seq_len,
            prefill_buckets=tpu_cfg.prefill_buckets,
            cache_dtype=dtype,
            decode_block=getattr(tpu_cfg, "decode_block", 1),
            kv_quant=tpu_cfg.kv_quantization == "int8",
            pipeline_microbatches=tpu_cfg.pipeline_microbatches,
            prefill_chunk=getattr(tpu_cfg, "prefill_chunk", 256),
            prefill_token_budget=getattr(tpu_cfg, "prefill_token_budget",
                                         None),
            prefix_cache_bytes=int(
                (getattr(tpu_cfg, "prefix_cache_mb", None) or 0) * 2**20),
            prefix_block_tokens=int(
                getattr(tpu_cfg, "prefix_block_tokens", None) or 16),
            prefix_gossip_blocks=int(
                getattr(tpu_cfg, "prefix_gossip_blocks", None) or 0),
            # is-None, not falsy-or: an explicit 0.0 means "recompute
            # on every heartbeat probe", not the default cadence
            prefix_gossip_s=float(
                2.0 if getattr(tpu_cfg, "prefix_gossip_s", None) is None
                else tpu_cfg.prefix_gossip_s),
            speculative=SpecConfig.from_knob(
                getattr(tpu_cfg, "speculative", None)),
            fused_dequant=bool(getattr(tpu_cfg, "fused_dequant", False)),
            profile_sample=int(
                getattr(tpu_cfg, "profile_sample", 0) or 0),
            # "disagg" is the BACKEND's role (it spawns a prefill and a
            # decode host, each of which sees its own tier role here);
            # an engine can only be one tier or unified.
            role=getattr(tpu_cfg, "role", "unified") or "unified",
        )
