"""Tokenization + chat templating for the engine.

Two implementations behind one interface:
  - HFTokenizer: wraps a local HF tokenizer dir (llama3/mistral production
    path; no network — the checkpoint dir ships tokenizer files).
  - ByteTokenizer: UTF-8 bytes as ids 0-255 plus BOS/EOS — deterministic,
    dependency-free, pairs with the `tiny` model preset so the whole serving
    stack runs in tests (SURVEY §4: engine tests against tiny real models).

Detokenization is incremental: decode() may be called per generated token,
and multi-byte codepoints must not be emitted until complete — the stream
the provider forwards is text chunks, and a split UTF-8 sequence would
corrupt the client's view (reference hot loop forwards backend chunks
verbatim, src/provider.ts:247; here WE are the backend producing them).
"""

from __future__ import annotations

import abc


class Tokenizer(abc.ABC):
    bos_id: int
    eos_ids: frozenset[int]
    vocab_size: int

    @abc.abstractmethod
    def encode(self, text: str, *, bos: bool = True) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: list[int]) -> str: ...

    @abc.abstractmethod
    def apply_chat_template(self, messages: list[dict[str, str]]) -> list[int]:
        """Render a chat and leave the sequence open for the assistant turn."""

    def stream_decoder(self) -> "StreamDecoder":
        return StreamDecoder(self)


class StreamDecoder:
    """Incremental detokenizer: feed ids, get only newly-completed text.

    Decodes only a sliding window of recent ids (never the whole history), so
    per-token cost is O(window), not O(generated-so-far): `_prefix` marks where
    the last emitted text's token context starts, `_read` where unemitted ids
    begin. Both advance together once a push produces clean (no trailing
    replacement char) text, which bounds the window at a few ids in practice.
    """

    def __init__(self, tok: Tokenizer) -> None:
        self._tok = tok
        self._ids: list[int] = []
        self._prefix = 0  # context window start
        self._read = 0    # first id not yet emitted as text

    def push(self, token_id: int) -> str:
        # One id is the degenerate batch: the back-off loop collapses to
        # push's old hold-everything-back behavior, and keeping a single
        # implementation means the hold-back rules cannot drift.
        return self.push_many([token_id])

    def push_many(self, token_ids: list[int]) -> str:
        """Feed a whole run of ids in ONE pass; return the newly-completed
        text. Equivalent to ``"".join(push(t) for t in token_ids)`` but with
        O(1) decode calls per run instead of O(len) — the batch API the
        block-granular emit path uses (one call per slot per decode block).

        A trailing incomplete codepoint is held back exactly as push()
        holds it: back off id-by-id from the end (a codepoint spans at most
        a few ids) to the last clean boundary and emit up to there."""
        if not token_ids:
            return ""
        self._ids.extend(token_ids)
        prefix_text = self._tok.decode(self._ids[self._prefix:self._read])
        end = len(self._ids)
        text = self._tok.decode(self._ids[self._prefix:end])
        while text.endswith("�") and end > self._read:
            # Mid-codepoint tail: shrink the emitted run until clean. Ids
            # past `end` stay buffered for the next push/flush.
            end -= 1
            text = self._tok.decode(self._ids[self._prefix:end])
        if text.endswith("�") or end <= self._read:
            return ""  # the whole unemitted run is mid-codepoint
        delta = text[len(prefix_text):]
        self._prefix = self._read
        self._read = end
        return delta

    def flush(self) -> str:
        text = self._tok.decode(self._ids[self._prefix:])
        prefix_text = self._tok.decode(self._ids[self._prefix:self._read])
        self._prefix = self._read = len(self._ids)
        return text[len(prefix_text):]


def resolve_resume(tokenizer: Tokenizer, resume: dict | None,
                   prompt_ids: list[int], max_new: int
                   ) -> tuple[list[int], int, int]:
    """ONE implementation of resume-request resolution, shared by every
    admission path (host submit, decode-tier adopt, in-process backend —
    divergent copies already disagreed once on negative-count handling):
    returns (prompt_ids + re-encoded emitted continuation, remaining
    token budget, resume offset). The client's claimed token count wins
    (it positions the seeded RNG lane exactly); the re-encoded length
    stands in when the shed couldn't stamp one. Raises ValueError on a
    negative claim — a malformed resume must be rejected, not inflate
    the budget past the client's max_tokens.

    A remaining budget of ZERO is meaningful: the interrupted stream had
    already emitted the whole max_tokens budget (the crash ate only the
    finish frame). Callers must then complete the request immediately
    with finish_reason "length" and no new tokens — flooring to 1 here
    would generate one token past the client's budget and break
    token-identity with the uninterrupted run (which stopped exactly at
    max_tokens)."""
    if not isinstance(resume, dict):
        return prompt_ids, max_new, 0
    text = str(resume.get("text") or "")
    emitted_ids = tokenizer.encode(text, bos=False) if text else []
    claimed = resume.get("tokens")
    offset = int(claimed) if claimed is not None else len(emitted_ids)
    if offset < 0:
        raise ValueError(f"resume tokens {offset} < 0")
    return prompt_ids + emitted_ids, max(0, max_new - offset), offset


class ByteTokenizer(Tokenizer):
    """ids 0-255 = raw bytes; 256 = BOS; 257 = EOS; ids >= 258 decode to
    byte (id % 256). vocab defaults to 258 (fits `tiny`).

    The modulo mapping matters for models whose vocab exceeds 258 served
    WITHOUT tokenizer files (benchmarks, smoke runs): a 128k-vocab model
    samples ids >= 258 essentially always, and silently dropping them
    (the old behavior) turns the entire stream into empty text deltas —
    round 3's e2e bench measured exactly that silence (every client's
    TTFT == wall time) before this fix. Construct with the model's
    vocab_size so sampled ids are meaningful byte text."""

    BOS, EOS = 256, 257

    def __init__(self, vocab_size: int = 258) -> None:
        self.bos_id = self.BOS
        self.eos_ids = frozenset({self.EOS})
        self.vocab_size = max(int(vocab_size), 258)

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] + ids) if bos else ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i % 256 for i in ids if i not in (self.BOS, self.EOS))
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict[str, str]]) -> list[int]:
        parts = [f"{m.get('role', 'user')}: {m.get('content', '')}\n"
                 for m in messages]
        parts.append("assistant: ")
        return self.encode("".join(parts), bos=True)


class HFTokenizer(Tokenizer):
    """transformers AutoTokenizer over local files only."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id
        eos = self._tok.eos_token_id
        ids = {eos} if isinstance(eos, int) else set(eos or ())
        # llama3 chat ends turns with <|eot_id|>, distinct from eos.
        for special in ("<|eot_id|>", "<|im_end|>"):
            sid = self._tok.convert_tokens_to_ids(special)
            if isinstance(sid, int) and sid >= 0:
                ids.add(sid)
        self.eos_ids = frozenset(ids)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=bos)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict[str, str]]) -> list[int]:
        if self._tok.chat_template is not None:
            return self._tok.apply_chat_template(
                messages, add_generation_prompt=True, tokenize=True
            )
        parts = [f"{m.get('role', 'user')}: {m.get('content', '')}\n"
                 for m in messages]
        parts.append("assistant: ")
        return self.encode("".join(parts), bos=True)


def get_tokenizer(tokenizer_path: str | None,
                  vocab_size: int = 258) -> Tokenizer:
    """tokenizer_path -> HFTokenizer; else a ByteTokenizer sized to the
    MODEL's vocab (so sampled ids stream as text, see ByteTokenizer)."""
    if tokenizer_path:
        return HFTokenizer(tokenizer_path)
    return ByteTokenizer(vocab_size)
