"""Weight loading: HF safetensors → sharded on-device params.

Serving-side "checkpoint/resume" (SURVEY §5.4): the TPU analog of the
reference's nonexistent model state is weight loading, and the hard
constraint is host RAM (SURVEY §7 hard-part 5: llama3-70b must not
materialize on the host). Strategy:

  - `jax.make_array_from_callback` per parameter: XLA asks for exactly the
    index-slice each local device needs, and the callback reads just that
    slice from the memory-mapped safetensors files (`get_slice`). Host
    footprint = one device shard at a time; on multi-host, each host only
    ever touches its own shards.
  - The stacked-layers layout ([L, ...] scanned by the model) is assembled
    slice-wise: a request for layers l0:l1 reads those layers' HF tensors
    only.
  - HF linear weights are [out, in]; ours are [in, out]. Transposition is
    folded into the slice read (swap the requested index, transpose the
    small result), never applied to the full tensor.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from symmetry_tpu.models.llama import (
    HF_EXPERT_MAP,
    HF_LAYER_MAP,
    HF_MOE_ROUTER,
    HF_TOP_MAP,
    ModelConfig,
    config_from_hf,
    hf_expert_name,
    init_params,
    param_logical_axes,
)
from symmetry_tpu.parallel.sharding import shardings_for


class CheckpointError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# In-memory conversion (tests, tiny models, torch-exported dicts)


def convert_hf_state_dict(
    tensors: dict[str, np.ndarray], config: ModelConfig
) -> dict:
    """Convert a full in-memory HF llama/mixtral state dict to our pytree."""
    n_exp = getattr(config, "num_experts", 0)
    per_layer: dict[str, list] = {
        ours: [None] * config.num_layers
        for ours, _ in HF_LAYER_MAP.values()
        # bias params exist only for attention_bias (qwen2) configs
        if config.attention_bias or ours not in ("bq", "bk", "bv")}
    if n_exp:
        # MoE FFN params come per (layer, expert); stack experts inside
        # each layer. The dense FFN names are absent in mixtral files.
        for ours in ("wg", "wu", "wd"):
            per_layer[ours] = [[None] * n_exp
                               for _ in range(config.num_layers)]
        per_layer["router"] = [None] * config.num_layers
    top: dict[str, np.ndarray] = {}
    for name, arr in tensors.items():
        if name in HF_TOP_MAP:
            ours, transpose = HF_TOP_MAP[name]
            top[ours] = arr.T if transpose else arr
        elif name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_str, _, sub = rest.partition(".")
            layer = int(idx_str)
            if n_exp and sub == HF_MOE_ROUTER:
                per_layer["router"][layer] = arr.T
            elif n_exp and sub.startswith("block_sparse_moe.experts."):
                parts = sub.split(".")       # experts . <e> . w1 . weight
                expert, w = int(parts[2]), parts[3]
                if w not in HF_EXPERT_MAP:
                    raise CheckpointError(f"unmapped HF tensor {name!r}")
                per_layer[HF_EXPERT_MAP[w]][layer][expert] = arr.T
            elif sub in HF_LAYER_MAP:
                ours, transpose = HF_LAYER_MAP[sub]
                if ours not in per_layer:
                    raise CheckpointError(
                        f"checkpoint has {name!r} but the config does not "
                        f"enable attention_bias")
                per_layer[ours][layer] = arr.T if transpose else arr
            else:
                raise CheckpointError(f"unmapped HF tensor {name!r}")
        else:
            raise CheckpointError(f"unmapped HF tensor {name!r}")

    if n_exp:
        for ours in ("wg", "wu", "wd"):
            per_layer[ours] = [np.stack(experts) if all(
                e is not None for e in experts) else None
                for experts in per_layer[ours]]
    for ours, lst in per_layer.items():
        missing = [i for i, a in enumerate(lst) if a is None]
        if missing:
            raise CheckpointError(f"missing layers {missing} for param {ours!r}")

    params: dict = {
        "embed": top["embed"],
        "layers": {ours: np.stack(lst) for ours, lst in per_layer.items()},
        "final_norm": top["final_norm"],
    }
    if not config.tie_embeddings:
        if "lm_head" not in top:
            raise CheckpointError("checkpoint lacks lm_head and config is untied")
        params["lm_head"] = top["lm_head"]
    return params


# ---------------------------------------------------------------------------
# Streaming safetensors loading


class _SafetensorsDir:
    """Index over one or many .safetensors files in an HF checkpoint dir."""

    def __init__(self, path: str) -> None:
        from safetensors import safe_open

        self._open = safe_open
        self._files: dict[str, str] = {}  # tensor name -> file path
        index_path = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path, "r", encoding="utf-8") as fh:
                index = json.load(fh)
            for name, fname in index["weight_map"].items():
                self._files[name] = os.path.join(path, fname)
        else:
            single = [f for f in sorted(os.listdir(path))
                      if f.endswith(".safetensors")]
            if not single:
                raise CheckpointError(f"no .safetensors files under {path}")
            for fname in single:
                fpath = os.path.join(path, fname)
                with safe_open(fpath, framework="np") as f:
                    for name in f.keys():
                        self._files[name] = fpath
        self._handles: dict[str, Any] = {}

    def names(self) -> Iterator[str]:
        return iter(self._files)

    def _handle(self, name: str):
        fpath = self._files[name]
        if fpath not in self._handles:
            self._handles[fpath] = self._open(fpath, framework="np")
        return self._handles[fpath]

    def read_slice(self, name: str, index: tuple[slice, ...],
                   transpose: bool) -> np.ndarray:
        """Read tensor[index] where index refers to the (maybe-transposed)
        logical layout we store; the file read is of the swapped index."""
        if name not in self._files:
            raise CheckpointError(f"tensor {name!r} not in checkpoint")
        sl = self._handle(name).get_slice(name)
        if transpose:
            r, c = index
            return np.ascontiguousarray(sl[c, r].T)
        return sl[index]


def _norm_index(index, ndim: int) -> tuple[slice, ...]:
    """Expand a device index (possibly Ellipsis/short) to one slice per dim."""
    if index is Ellipsis:
        return (slice(None),) * ndim
    index = tuple(index)
    out = []
    for ix in index:
        if ix is Ellipsis:
            out.extend([slice(None)] * (ndim - len(index) + 1))
        else:
            out.append(ix)
    out.extend([slice(None)] * (ndim - len(out)))
    return tuple(out)


def load_checkpoint(
    path: str,
    config: ModelConfig | None = None,
    *,
    mesh=None,
    rules: dict[str, str | None] | None = None,
    dtype=jnp.bfloat16,
) -> tuple[dict, ModelConfig]:
    """Load an HF llama-family checkpoint dir into sharded device arrays.

    Returns (params, config). If `config` is None it is derived from the
    checkpoint's config.json. With no mesh, arrays land unsharded on the
    default device (single-chip path).
    """
    if config is None:
        cfg_path = os.path.join(path, "config.json")
        if not os.path.exists(cfg_path):
            raise CheckpointError(f"no config.json under {path}")
        with open(cfg_path, "r", encoding="utf-8") as fh:
            config = config_from_hf(json.load(fh))

    store = _SafetensorsDir(path)
    names = set(store.names())
    tied = config.tie_embeddings or "lm_head.weight" not in names

    axes = param_logical_axes(config)
    abstract = jax.eval_shape(
        lambda: init_params(config, jax.random.key(0), dtype))
    if tied and "lm_head" in abstract:
        raise CheckpointError("checkpoint ties embeddings but config does not")

    if mesh is not None:
        shardings = shardings_for(axes, mesh, rules)
    else:
        dev = jax.devices()[0]
        shardings = jax.tree.map(lambda _: jax.sharding.SingleDeviceSharding(dev),
                                 abstract)

    inv_layer = {ours: (hf, t) for hf, (ours, t) in HF_LAYER_MAP.items()}
    inv_top = {ours: (hf, t) for hf, (ours, t) in HF_TOP_MAP.items()}

    def top_reader(ours: str) -> Callable:
        hf_name, transpose = inv_top[ours]

        def read(index):
            ndim = len(abstract[ours].shape)
            arr = store.read_slice(hf_name, _norm_index(index, ndim), transpose)
            return arr.astype(dtype)

        return read

    n_exp = getattr(config, "num_experts", 0)

    def layer_reader(ours: str) -> Callable:
        if n_exp and ours == "router":
            def read(index):
                l_sl, *rest = _norm_index(index, 3)
                layers = range(*l_sl.indices(config.num_layers))
                per = [store.read_slice(
                    f"model.layers.{l}.{HF_MOE_ROUTER}", tuple(rest), True)
                    for l in layers]
                return np.stack(per).astype(dtype)

            return read
        if n_exp and ours in ("wg", "wu", "wd"):
            def read(index):
                # stacked [L, X, in, out]: one HF tensor per (layer, expert)
                l_sl, x_sl, *rest = _norm_index(index, 4)
                layers = range(*l_sl.indices(config.num_layers))
                experts = range(*x_sl.indices(n_exp))
                per = [np.stack([store.read_slice(
                    hf_expert_name(l, e, ours), tuple(rest), True)
                    for e in experts]) for l in layers]
                return np.stack(per).astype(dtype)

            return read
        hf_sub, transpose = inv_layer[ours]

        def read(index):
            ndim = len(abstract["layers"][ours].shape)
            l_sl, *rest = _norm_index(index, ndim)
            layers = range(*l_sl.indices(config.num_layers))
            per = [store.read_slice(f"model.layers.{l}.{hf_sub}",
                                    tuple(rest), transpose)
                   for l in layers]
            return np.stack(per).astype(dtype)

        return read

    def materialize(ours_path: tuple, aval, sharding) -> jax.Array:
        if ours_path[0] == "layers":
            read = layer_reader(ours_path[1])
        else:
            read = top_reader(ours_path[0])
        return jax.make_array_from_callback(aval.shape, sharding,
                                            lambda ix: read(ix))

    params = {
        "embed": materialize(("embed",), abstract["embed"], shardings["embed"]),
        "layers": {
            k: materialize(("layers", k), abstract["layers"][k],
                           shardings["layers"][k])
            for k in abstract["layers"]
        },
        "final_norm": materialize(("final_norm",), abstract["final_norm"],
                                  shardings["final_norm"]),
    }
    if "lm_head" in abstract:
        params["lm_head"] = materialize(("lm_head",), abstract["lm_head"],
                                        shardings["lm_head"])
    return params, config


def save_checkpoint(path: str, params: dict, config: ModelConfig) -> None:
    """Write params back out as a single HF-layout safetensors file (tests,
    tiny-model fixtures, re-export of quantized weights)."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}
    inv_top = {ours: (hf, t) for hf, (ours, t) in HF_TOP_MAP.items()}
    for ours in ("embed", "final_norm", "lm_head"):
        if ours not in params:
            continue
        hf_name, transpose = inv_top[ours]
        arr = np.asarray(jax.device_get(params[ours]), dtype=np.float32)
        tensors[hf_name] = np.ascontiguousarray(arr.T) if transpose else arr
    n_exp = getattr(config, "num_experts", 0)
    for ours, stacked in params["layers"].items():
        host = np.asarray(jax.device_get(stacked), dtype=np.float32)
        if n_exp and ours == "router":
            for l in range(host.shape[0]):
                tensors[f"model.layers.{l}.{HF_MOE_ROUTER}"] = (
                    np.ascontiguousarray(host[l].T))
            continue
        if n_exp and ours in ("wg", "wu", "wd"):
            for l in range(host.shape[0]):
                for e in range(host.shape[1]):
                    tensors[hf_expert_name(l, e, ours)] = (
                        np.ascontiguousarray(host[l, e].T))
            continue
        hf_sub, transpose = {v[0]: (k, v[1]) for k, v in HF_LAYER_MAP.items()}[ours]
        for l in range(host.shape[0]):
            arr = host[l]
            tensors[f"model.layers.{l}.{hf_sub}"] = (
                np.ascontiguousarray(arr.T) if transpose else np.ascontiguousarray(arr))
    save_file(tensors, os.path.join(path, "model.safetensors"))
    hf_cfg = {
        "architectures": ["MixtralForCausalLM" if n_exp
                          else ("Qwen2ForCausalLM" if config.attention_bias
                                else "LlamaForCausalLM")],
        "attention_bias": config.attention_bias,
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_size,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "num_key_value_heads": config.num_kv_heads,
        "intermediate_size": config.intermediate_size,
        "rope_theta": config.rope_theta,
        "rms_norm_eps": config.rms_eps,
        "tie_word_embeddings": config.tie_embeddings,
        "max_position_embeddings": config.max_position,
        "sliding_window": config.sliding_window,
        "head_dim": config.head_dim,
    }
    if n_exp:
        hf_cfg["num_local_experts"] = n_exp
        hf_cfg["num_experts_per_tok"] = config.num_experts_per_tok
    with open(os.path.join(path, "config.json"), "w", encoding="utf-8") as fh:
        json.dump(hf_cfg, fh, indent=2)


# ---------------------------------------------------------------------------
# Warm restart cache (SURVEY §5.4: orbax-style cached sharded weights)
#
# Loading a big checkpoint costs safetensors streaming + HF-layout
# transposition + layer stacking + (for int8 serving) quantization of
# every matmul weight. All of it is deterministic in (checkpoint, dtype,
# quantize), so the first load persists the FINISHED param tree — stacked
# layers, our layout, already quantized — and every restart after that is
# a flat mmap read straight to device. No transposes, no quantize pass.

_WARM_DIR = ".symmetry_warm"
_WARM_VERSION = 1


def _warm_path(checkpoint_path: str, dtype, quantize: bool) -> str:
    tag = f"v{_WARM_VERSION}-{jnp.dtype(dtype).name}-{'int8' if quantize else 'dense'}"
    return os.path.join(checkpoint_path, _WARM_DIR, tag)


def _flatten_params(params: dict, prefix: str = "") -> Iterator[tuple[str, Any]]:
    from symmetry_tpu.ops.quant import (
        PackedQuantizedTensor, QuantizedTensor, unpack_quantized)

    for name, child in sorted(params.items()):
        path = f"{prefix}{name}"
        if isinstance(child, dict):
            yield from _flatten_params(child, path + "/")
        elif isinstance(child, PackedQuantizedTensor):
            # The cache stores the FLAT int8 layout: tile geometry is a
            # kernel tuning detail (tpu.fused_dequant re-packs at engine
            # construction), not checkpoint state — a cache written by a
            # fused build must stay readable by a non-fused one.
            flat = unpack_quantized(child)
            yield path + ":q", flat.q
            yield path + ":scale", flat.scale
        elif isinstance(child, QuantizedTensor):
            yield path + ":q", child.q
            yield path + ":scale", child.scale
        else:
            yield path, child


def _checkpoint_fingerprint(checkpoint_path: str) -> list[list]:
    """(name, mtime, size) of every source file the cache derives from —
    recorded at save, verified at load, so an overwritten checkpoint can
    never be silently served from a stale cache."""
    out = []
    for fname in sorted(os.listdir(checkpoint_path)):
        if fname.endswith(".safetensors") or fname in (
                "config.json", "model.safetensors.index.json"):
            st = os.stat(os.path.join(checkpoint_path, fname))
            out.append([fname, round(st.st_mtime, 3), st.st_size])
    return out


# Host-RAM guard for the cache WRITE: save_file needs the whole tree as
# host arrays at once. Int8-quantized 70B is ~35 GB — fine on TPU hosts —
# but an operator can cap or disable via this env var.
_WARM_MAX_BYTES = int(float(os.environ.get(
    "SYMMETRY_WARM_CACHE_MAX_GB", "64")) * 1e9)


def save_warm_cache(checkpoint_path: str, params: dict, config: ModelConfig,
                    *, dtype, quantize: bool) -> None:
    """Persist a finished param tree next to its checkpoint (best effort —
    failure to cache must never fail serving). bfloat16 leaves are stored
    as uint16 views with the dtype recorded, so the file has no
    non-numpy-native dtypes. The write is ATOMIC (temp dir + rename): a
    crash mid-save must leave no half-cache a later load could trip on."""
    import dataclasses
    import shutil
    import tempfile

    from safetensors.numpy import save_file

    total = sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
                for _, leaf in _flatten_params(params))
    if total > _WARM_MAX_BYTES:
        raise RuntimeError(
            f"param tree is {total/1e9:.1f} GB > "
            f"SYMMETRY_WARM_CACHE_MAX_GB; not caching")

    out_dir = _warm_path(checkpoint_path, dtype, quantize)
    tensors: dict[str, np.ndarray] = {}
    dtypes: dict[str, str] = {}
    for name, leaf in _flatten_params(params):
        host = np.asarray(jax.device_get(leaf))
        dtypes[name] = str(leaf.dtype)
        if host.dtype.name not in ("float32", "float16", "int8", "int32",
                                   "uint16"):
            if host.dtype.itemsize != 2:
                # the uint16-view trick is only shape-preserving for
                # 2-byte dtypes; anything else must fail loudly here,
                # not corrupt shapes at load
                raise RuntimeError(
                    f"unsupported warm-cache dtype {host.dtype} for {name}")
            host = host.view(np.uint16)  # bfloat16 and friends
        tensors[name] = np.ascontiguousarray(host)
    os.makedirs(os.path.dirname(out_dir), exist_ok=True)
    tmp_dir = tempfile.mkdtemp(dir=os.path.dirname(out_dir))
    try:
        save_file(tensors, os.path.join(tmp_dir, "params.safetensors"))
        meta = {
            "version": _WARM_VERSION,
            "config_class": type(config).__name__,
            "config": dataclasses.asdict(config),
            "dtypes": dtypes,
            "fingerprint": _checkpoint_fingerprint(checkpoint_path),
        }
        with open(os.path.join(tmp_dir, "meta.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(meta, fh)
        if os.path.exists(out_dir):
            shutil.rmtree(out_dir)
        os.rename(tmp_dir, out_dir)
    except BaseException:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise


def load_warm_cache(checkpoint_path: str, *, dtype, quantize: bool,
                    mesh=None, rules=None) -> tuple[dict, ModelConfig] | None:
    """Load a warm cache written by save_warm_cache; None when absent or
    unreadable (callers fall back to the full checkpoint load). Sharded
    meshes read per-device slices via make_array_from_callback, exactly
    like the cold path — each host only touches its own shards."""
    from symmetry_tpu.models.llama import ModelConfig as MC
    from symmetry_tpu.models.llama import MoEConfig
    from symmetry_tpu.ops.quant import QuantizedTensor

    out_dir = _warm_path(checkpoint_path, dtype, quantize)
    meta_path = os.path.join(out_dir, "meta.json")
    st_path = os.path.join(out_dir, "params.safetensors")
    if not (os.path.exists(meta_path) and os.path.exists(st_path)):
        return None
    try:
        with open(meta_path, "r", encoding="utf-8") as fh:
            meta = json.load(fh)
        if meta.get("version") != _WARM_VERSION:
            return None
        if meta.get("fingerprint") != _checkpoint_fingerprint(
                checkpoint_path):
            return None  # checkpoint changed since the cache was written
        cls = MoEConfig if meta["config_class"] == "MoEConfig" else MC
        config = cls(**meta["config"])
    except (ValueError, TypeError, KeyError, OSError):
        return None

    from safetensors import safe_open

    import ml_dtypes

    try:
        handle = safe_open(st_path, framework="np")
    except Exception:  # noqa: BLE001 — truncated/corrupt file → cold load
        return None
    dtypes = meta["dtypes"]

    if mesh is not None:
        from symmetry_tpu.models.llama import (
            param_logical_axes, quantized_logical_axes)

        axes = param_logical_axes(config)
        if quantize:
            axes = quantized_logical_axes(axes)
        shardings = shardings_for(axes, mesh, rules)
    else:
        dev = jax.devices()[0]
        shardings = None  # single device: whole-array reads

    def leaf_sharding(path_parts):
        node = shardings
        for part in path_parts:
            node = node[part] if isinstance(node, dict) else getattr(
                node, part)
        return node

    def read_leaf(name: str):
        want = np.dtype(ml_dtypes.bfloat16) if dtypes[name] == "bfloat16" \
            else np.dtype(dtypes[name])
        sl = handle.get_slice(name)

        def read(index):
            arr = sl[_norm_index(index, len(sl.get_shape()))]
            if arr.dtype == np.uint16 and want != np.uint16:
                arr = arr.view(want)
            return arr

        shape = tuple(sl.get_shape())
        if mesh is not None:
            parts = name.replace(":", "/").split("/")
            sharding = leaf_sharding(parts)
        else:
            sharding = jax.sharding.SingleDeviceSharding(dev)
        return jax.make_array_from_callback(shape, sharding, read)

    # rebuild the nested tree; ":q"/":scale" pairs fold into
    # QuantizedTensor leaves
    params: dict = {}
    pending_quant: dict[str, dict] = {}
    try:
        for name in handle.keys():
            arr = read_leaf(name)
            if ":" in name:
                base, _, part = name.partition(":")
                pending_quant.setdefault(base, {})[part] = arr
            else:
                _tree_set(params, name.split("/"), arr)
    finally:
        # every callback has run by now (make_array_from_callback is
        # synchronous) — release the fd/mmap of the multi-GB cache file
        # on EVERY path, including a failed read (the caller falls back
        # to the cold load and must not hold a stale mapping)
        if hasattr(handle, "__exit__"):
            handle.__exit__(None, None, None)
    for base, parts in pending_quant.items():
        _tree_set(params, base.split("/"),
                  QuantizedTensor(q=parts["q"], scale=parts["scale"]))
    return params, config


def _tree_set(tree: dict, parts: list[str], value) -> None:
    for p in parts[:-1]:
        tree = tree.setdefault(p, {})
    tree[parts[-1]] = value
