"""symledger: per-request device-time attribution and waste accounting.

symprof (utils/devprof.py) prices device time per dispatch KIND; this
module prices it per REQUEST. The scheduler apportions every dispatch's
measured wall to the slots it served — prefill/chunk dispatches exactly
(each dispatch names its requests), decode/verify block syncs split by
active-slot occupancy — and each request accumulates:

  device_s{phase}   attributed device seconds per phase
                    (prefill / chunk / decode / verify / adopt)
  queue_s           scheduler queue wait (enqueue -> placement pick)
  emit_s            share of emit-path delivery wall (best effort: the
                    terminal flush itself lands after the entry closes)
  wasted_s{reason}  device seconds spent on output nobody consumed —
                    rejected speculative drafts (spec_rejected), tokens
                    a resume regenerated then deduped (resume_discarded),
                    deadline sheds (deadline_shed — zero device by
                    construction, booked so the class is visible),
                    killed-in-flight partial prefill (killed_prefill),
                    and a mid-decode cancel's final block share
                    (cancelled)
  saved_s           prefill seconds a radix hit avoided, priced at the
                    admitting dispatch's own per-token rate

Attribution source is flagged, never guessed: "probed" when symprof
sampling is armed (probe syncs make the dispatch walls device-true),
"blocked" otherwise (dispatch-thread block time — an upper bound that
includes host-side dispatch overhead). Echo backends stamp "estimated".

Threading: the engine thread opens/books/finishes entries, the emit
worker books emit shares, and the host pipe thread reads stats() — one
coarse lock, critical sections of a few dict ops. Disabled mode
(tpu.ledger=false) follows the METRICS/FAULTS overhead contract:
`track()` returns None, so every scheduler booking site is one
`is not None` branch and no entry is ever allocated.

Conservation is the correctness pin (tests/test_ledger.py): the sum of
per-request `device_s` plus the unattributed residue (blocks whose
every lane went stale before sync) equals the scheduler's own
admit/adopt/chunk/sync walls within 5% under mixed traffic.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any

PHASES = ("prefill", "chunk", "decode", "verify", "adopt")
WASTE_REASONS = ("spec_rejected", "resume_discarded", "deadline_shed",
                 "killed_prefill", "cancelled")


def _round_map(d: dict[str, float]) -> dict[str, float]:
    return {k: round(v, 6) for k, v in d.items() if v}


class LedgerEntry:
    """One request's open cost account. Handle protocol (the lifecycle
    checker's ledger-entry spec): acquired via `RequestLedger.track`,
    resolved by `finish()` (builds the wire costs block) or `release()`
    (folds into aggregates without one) — both idempotent, so every
    exit path may close unconditionally."""

    __slots__ = ("_ledger", "req_id", "device_s", "queue_s", "emit_s",
                 "wasted_s", "wasted_tokens", "saved_s", "saved_tokens",
                 "tokens", "closed")

    def __init__(self, ledger: "RequestLedger", req_id: str) -> None:
        self._ledger = ledger
        self.req_id = req_id
        self.device_s: dict[str, float] = {}
        self.queue_s = 0.0
        self.emit_s = 0.0
        self.wasted_s: dict[str, float] = {}
        self.wasted_tokens: dict[str, int] = {}
        self.saved_s = 0.0
        self.saved_tokens = 0
        self.tokens = 0
        self.closed = False

    # ------------------------------------------------------------- booking

    def book_queue(self, seconds: float) -> None:
        """Set (not add): a budget-deferred request re-picks and the
        latest pick is the true wait."""
        with self._ledger._lock:
            if not self.closed:
                self.queue_s = max(0.0, seconds)

    def book_device(self, phase: str, seconds: float,
                    tokens: int = 0) -> None:
        if seconds <= 0.0 and not tokens:
            return
        led = self._ledger
        with led._lock:
            if seconds > 0.0:
                led._total_device[phase] = (
                    led._total_device.get(phase, 0.0) + seconds)
            if not self.closed:
                if seconds > 0.0:
                    self.device_s[phase] = (
                        self.device_s.get(phase, 0.0) + seconds)
                self.tokens += tokens

    def book_saved_at_phase_rate(self, phase: str, suffix_tokens: int,
                                 reused_tokens: int) -> None:
        """Saved seconds priced at THIS entry's own per-token rate for
        `phase` — the chunked-prefill path, where the admitting rate is
        only known after the chunks have run."""
        led = self._ledger
        with led._lock:
            if self.closed or reused_tokens <= 0:
                return
            rate = self.device_s.get(phase, 0.0) / max(1, suffix_tokens)
            self.saved_s += rate * reused_tokens
            self.saved_tokens += reused_tokens

    def book_saved(self, seconds: float, tokens: int) -> None:
        with self._ledger._lock:
            if not self.closed:
                self.saved_s += max(0.0, seconds)
                self.saved_tokens += tokens

    def book_wasted(self, reason: str, seconds: float,
                    tokens: int = 0) -> None:
        with self._ledger._lock:
            if not self.closed:
                self.wasted_s[reason] = (
                    self.wasted_s.get(reason, 0.0) + max(0.0, seconds))
                self.wasted_tokens[reason] = (
                    self.wasted_tokens.get(reason, 0) + tokens)

    def waste_all_device(self, reason: str, tokens: int = 0) -> None:
        """Reclassify everything booked so far as waste (a cancel mid
        chunked-prefill: the whole prefix built so far served nobody)."""
        with self._ledger._lock:
            if not self.closed:
                spent = sum(self.device_s.values())
                self.wasted_s[reason] = (
                    self.wasted_s.get(reason, 0.0) + spent)
                self.wasted_tokens[reason] = (
                    self.wasted_tokens.get(reason, 0) + tokens)

    def book_emit(self, seconds: float) -> None:
        led = self._ledger
        with led._lock:
            led._total_emit += max(0.0, seconds)
            if not self.closed:
                self.emit_s += max(0.0, seconds)

    # ------------------------------------------------------------- closing

    def costs(self) -> dict[str, Any]:
        """The wire `costs` block (host event -> StreamChunk ->
        INFERENCE_ENDED). Caller holds no lock; values are snapshotted
        under it."""
        with self._ledger._lock:
            return self._costs_locked()

    def _costs_locked(self) -> dict[str, Any]:
        device = _round_map(self.device_s)
        out: dict[str, Any] = {
            "device_s": device,
            "device_total_s": round(sum(self.device_s.values()), 6),
            "queue_s": round(self.queue_s, 6),
            "emit_s": round(self.emit_s, 6),
            # No zero-filter: deadline_shed books 0.0 device seconds by
            # construction and the class must still reach the wire.
            "wasted_s": {k: round(v, 6) for k, v in self.wasted_s.items()},
            "wasted_total_s": round(sum(self.wasted_s.values()), 6),
            "tokens": self.tokens,
            "source": self._ledger.source,
        }
        if self.wasted_tokens:
            out["wasted_tokens"] = {
                k: v for k, v in self.wasted_tokens.items() if v}
        if self.saved_tokens or self.saved_s:
            out["saved_s"] = round(self.saved_s, 6)
            out["saved_tokens"] = self.saved_tokens
        return out

    def finish(self, reason: str, tokens: int | None = None
               ) -> dict[str, Any] | None:
        """Close the entry and return the costs block for the terminal
        event. Idempotent: a second close (any exit path racing another)
        returns None and books nothing twice."""
        led = self._ledger
        with led._lock:
            if self.closed:
                return None
            self.closed = True
            if tokens is not None:
                self.tokens = tokens
            block = self._costs_locked()
            block["finish"] = reason
            led._fold_locked(self, reason, block)
            return block

    def release(self, reason: str = "released") -> None:
        """Close without a terminal event (prefill-tier handoff: the
        decode tier owns the finish). Idempotent."""
        led = self._ledger
        with led._lock:
            if self.closed:
                return
            self.closed = True
            block = self._costs_locked()
            block["finish"] = reason
            led._fold_locked(self, reason, block)


class RequestLedger:
    """The scheduler's cost ledger: live entries while requests run, a
    bounded ring of finished cost blocks, and cumulative aggregates
    (per finish reason + per phase) for the host STATS rider."""

    def __init__(self, *, enabled: bool = True, ring: int = 128,
                 measured: bool = False) -> None:
        self.enabled = bool(enabled)
        self.source = "probed" if measured else "blocked"
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(1, int(ring)))
        # Cumulative fleet totals: the conservation test's right-hand
        # side, and the aggregates the STATS rider ships. _total_device
        # includes an "unattributed" bucket for block syncs whose every
        # lane went stale before the sync landed.
        self._total_device: dict[str, float] = {}
        self._total_emit = 0.0
        self._total_wasted: dict[str, float] = {}
        self._total_wasted_tokens: dict[str, int] = {}
        self._total_saved_s = 0.0
        self._total_saved_tokens = 0
        self._total_tokens = 0
        self._live = 0
        self._finished = 0
        self._by_finish: dict[str, dict[str, float]] = {}

    # ------------------------------------------------------------- acquire

    def track(self, req_id: str) -> LedgerEntry | None:
        """Open a cost account; None while disabled (the one guarded
        branch every booking site then takes)."""
        if not self.enabled:
            return None
        entry = LedgerEntry(self, req_id)
        with self._lock:
            self._live += 1
        return entry

    def book_unattributed(self, seconds: float) -> None:
        """A block sync whose every snapshot lane was stale: real device
        wall, no live owner. Booked so conservation still closes."""
        if seconds <= 0.0:
            return
        with self._lock:
            self._total_device["unattributed"] = (
                self._total_device.get("unattributed", 0.0) + seconds)

    # -------------------------------------------------------------- folds

    def _fold_locked(self, entry: LedgerEntry, reason: str,
                     block: dict[str, Any]) -> None:
        self._live = max(0, self._live - 1)
        self._finished += 1
        for k, v in entry.wasted_s.items():
            self._total_wasted[k] = self._total_wasted.get(k, 0.0) + v
        for k, n in entry.wasted_tokens.items():
            self._total_wasted_tokens[k] = (
                self._total_wasted_tokens.get(k, 0) + n)
        self._total_saved_s += entry.saved_s
        self._total_saved_tokens += entry.saved_tokens
        self._total_tokens += entry.tokens
        agg = self._by_finish.setdefault(
            reason, {"requests": 0, "device_s": 0.0, "tokens": 0})
        agg["requests"] += 1
        agg["device_s"] += sum(entry.device_s.values())
        agg["tokens"] += entry.tokens
        if entry.req_id:
            block = dict(block)
            block["id"] = entry.req_id
        self._ring.append(block)

    # -------------------------------------------------------------- stats

    def device_total_s(self) -> float:
        with self._lock:
            return sum(self._total_device.values())

    def totals_brief(self) -> tuple[float, float]:
        """(attributed device seconds, wasted seconds), one lock hop —
        the scheduler's per-finish Perfetto counter stamps."""
        with self._lock:
            return (sum(self._total_device.values()),
                    sum(self._total_wasted.values()))

    def stats(self, ring_tail: int = 32) -> dict[str, Any]:
        """The host STATS `ledger` rider: bounded finished ring tail +
        cumulative aggregates. Never called on the hot loop."""
        with self._lock:
            total_dev = sum(self._total_device.values())
            total_waste = sum(self._total_wasted.values())
            out: dict[str, Any] = {
                "enabled": self.enabled,
                "source": self.source,
                "live": self._live,
                "finished": self._finished,
                "tokens": self._total_tokens,
                "device_s": _round_map(self._total_device),
                "device_total_s": round(total_dev, 6),
                "emit_s": round(self._total_emit, 6),
                # No zero-filter here: deadline_shed books 0.0 device
                # seconds by construction and the class must still show.
                "wasted_s": {k: round(v, 6)
                             for k, v in self._total_wasted.items()},
                "wasted_total_s": round(total_waste, 6),
                "wasted_tokens": dict(self._total_wasted_tokens),
                "wasted_share": (round(total_waste / total_dev, 4)
                                 if total_dev > 1e-12 else 0.0),
                "saved_s": round(self._total_saved_s, 6),
                "saved_tokens": self._total_saved_tokens,
                "by_finish": {
                    k: {"requests": int(v["requests"]),
                        "device_s": round(v["device_s"], 6),
                        "tokens": int(v["tokens"])}
                    for k, v in self._by_finish.items()},
                "ring": list(self._ring)[-max(0, int(ring_tail)):],
            }
            # Fleet goodput denominator precomputed for consumers that
            # only see the rider (symtop, bench): tokens per attributed
            # device second, all finish reasons included — the SLO cut
            # happens provider-side where attainment is known.
            if total_dev > 1e-12:
                out["tokens_per_device_s"] = round(
                    self._total_tokens / total_dev, 2)
            return out
